"""Optimized host collective algorithms + decision rules.

≙ the reference's algorithm library ompi/mca/coll/base/ (SURVEY.md Appendix A)
plus coll/tuned's decision machinery (coll_tuned_decision_fixed.c:55-104,
dynamic rules file coll_tuned_dynamic_file.c:58).

Algorithms implemented (reference file:line for the original; the full
SURVEY.md Appendix A inventory — linear/in-order baselines live in
coll/basic.py):
  allreduce: nonoverlapping reduce+bcast (coll_base_allreduce.c:57),
             recursive-doubling (:133), ring (:344),
             segmented/pipelined ring (:621),
             Rabenseifner reduce-scatter+allgather (:973),
             allgather+local-reduce (:1267)
  bcast:     pipeline (coll_base_bcast.c:277), chain (:305),
             binomial tree (:333), split-binary tree (:361),
             knomial (:720), scatter+allgather[-ring] (:774/:951)
  reduce:    chain (coll_base_reduce.c:384), pipeline (:414),
             binomial tree (:476), in-order binary for
             non-commutative ops (:514), Rabenseifner
             reduce-scatter+gather (:811), knomial (:1166)
  allgather: recursive-doubling (coll_base_allgather.c:85),
             sparbit (:227), ring (:330), neighbor-exchange (:456),
             two-procs (:570), [k-]bruck (:767),
             direct-messaging (:930)
  allgatherv: bruck (coll_base_allgatherv.c:95), sparbit (:259),
             ring (:371), neighbor-exchange (:498), two-procs (:643)
  alltoall:  pairwise (coll_base_alltoall.c:180), bruck (:239),
             linear-sync (:378), two-procs (:537)
  alltoallv: pairwise (coll_base_alltoallv.c:194)
  reduce_scatter: recursive-halving (coll_base_reduce_scatter.c:132),
             ring (:456), butterfly any-size/any-counts (:691)
  reduce_scatter_block: recursive-halving (:132 adapted),
             recursive-doubling (coll_base_reduce_scatter_block.c:197),
             butterfly (:691)
  barrier:   double-ring (coll_base_barrier.c:116),
             recursive-doubling/bruck (:188/:269), two-procs (:307),
             tree (:427)
  gather:    binomial (coll_base_gather.c:41), linear-sync (:208)
  scatter:   binomial (coll_base_scatter.c:63), non-blocking linear (:289)
  scan/exscan: recursive-doubling prefix (coll_base_scan.c:157)

Selection: fixed size/msg-size rules, overridable per-collective with the
``coll_tuned_<name>_algorithm`` variable and via a dynamic rules file named
by ``coll_tuned_dynamic_rules`` (lines: ``<coll> <min_comm> <min_bytes>
<algorithm>``, later lines win — the user-tunable escape hatch the reference
ships for cluster-specific tuning).

Non-commutative ops fall back to the in-order linear algorithms
(≙ coll_base_reduce.c:514 in-order binary for non-commutative).
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import numpy as np

from ..core import var as _var
from ..core.component import Component, component
from ..op import Op
from ..p2p.request import wait_all
from .basic import BasicModule, T_ALLGATHER, T_ALLTOALL, T_BARRIER, T_BCAST, \
    T_GATHER, T_REDUCE, T_RSCAT, T_SCAN, T_SCATTER, _inplace
from .framework import CollModule


def _sum_default(op):
    from .. import op as _op
    op = op or _op.SUM
    if op.name == "avg":
        # decision plumbing for the quantized device tier: AVG has no
        # pairwise fold, so no host algorithm can carry it — only the
        # device plane's coll/quant arm (which finalizes sum/size) can.
        raise ValueError(
            "AVG reductions are only implemented by the quantized device "
            "tier (coll/quant); host buffers must use SUM and divide, or "
            "move to the device plane")
    return op


# ---------------------------------------------------------------------------
# allreduce algorithms
# ---------------------------------------------------------------------------

def allreduce_recursive_doubling(comm, send: np.ndarray, recv: np.ndarray,
                                 op: Op) -> None:
    """coll_base_allreduce.c:133 — log2(p) rounds, full vector each round.
    Best for small messages. Non-power-of-2 handled with the standard
    fold-in/fold-out of extra ranks."""
    size, rank = comm.size, comm.rank
    recv[...] = send
    pof2 = 1 << (size.bit_length() - 1)
    rem = size - pof2
    tmp = np.empty_like(recv)
    # fold extras: ranks [0, 2*rem) pair up (even sends to odd)
    if rank < 2 * rem:
        if rank % 2 == 0:
            comm.send(recv, rank + 1, T_REDUCE)
            newrank = -1
        else:
            comm.recv(tmp, rank - 1, T_REDUCE)
            recv[...] = op(tmp, recv)
            newrank = rank // 2
    else:
        newrank = rank - rem
    if newrank >= 0:
        mask = 1
        while mask < pof2:
            peer_new = newrank ^ mask
            peer = peer_new * 2 + 1 if peer_new < rem else peer_new + rem
            comm.sendrecv(recv, peer, tmp, peer, T_REDUCE, T_REDUCE)
            if op.commutative or peer < rank:
                recv[...] = op(tmp, recv)
            else:
                recv[...] = op(recv.copy(), tmp)
            mask <<= 1
    # unfold
    if rank < 2 * rem:
        if rank % 2 == 0:
            comm.recv(recv, rank + 1, T_REDUCE)
        else:
            comm.send(recv, rank - 1, T_REDUCE)


def _ring_bounds(n: int, size: int) -> np.ndarray:
    """Chunk boundaries of the ring schedule (np.array_split convention:
    the first n%size chunks get the extra element) — the ONE partitioning
    both ring allreduce variants and their allgather phases share."""
    base, extra = divmod(n, size)
    sizes = np.full(size, base, np.int64)
    sizes[:extra] += 1
    return np.concatenate([[0], np.cumsum(sizes)])


def _ring_allgather_phase(comm, flat: np.ndarray, bounds: np.ndarray,
                          tag: int) -> None:
    """The p-1 allgather rounds shared by ring and segmented-ring
    allreduce: each step forwards the chunk received last step."""
    size, rank = comm.size, comm.rank
    right, left = (rank + 1) % size, (rank - 1) % size
    for step in range(size - 1):
        s = (rank + 1 - step) % size
        r = (rank - step) % size
        inbox = np.empty(int(bounds[r + 1] - bounds[r]), flat.dtype)
        comm.sendrecv(flat[bounds[s]:bounds[s + 1]], right, inbox, left,
                      tag, tag)
        flat[bounds[r]:bounds[r + 1]] = inbox


def allreduce_ring(comm, send: np.ndarray, recv: np.ndarray, op: Op) -> None:
    """coll_base_allreduce.c:344 — reduce-scatter ring then allgather ring;
    bandwidth-optimal 2(p-1)/p·n bytes per rank. The identical neighbor-
    exchange schedule ring attention uses (SURVEY.md §5.7)."""
    size, rank = comm.size, comm.rank
    recv[...] = send
    if size == 1:
        return
    flat = recv.reshape(-1)
    bounds = _ring_bounds(flat.size, size)
    right, left = (rank + 1) % size, (rank - 1) % size
    # reduce-scatter phase
    for step in range(size - 1):
        s = (rank - step) % size
        r = (rank - step - 1) % size
        inbox = np.empty(int(bounds[r + 1] - bounds[r]), flat.dtype)
        comm.sendrecv(flat[bounds[s]:bounds[s + 1]], right, inbox, left,
                      T_REDUCE, T_REDUCE)
        seg = flat[bounds[r]:bounds[r + 1]]
        seg[...] = op(inbox, seg)
    _ring_allgather_phase(comm, flat, bounds, T_ALLGATHER)


def allreduce_rabenseifner(comm, send: np.ndarray, recv: np.ndarray,
                           op: Op) -> None:
    """coll_base_allreduce.c:973 — recursive-halving reduce-scatter followed
    by recursive-doubling allgather; best large-message algorithm on trees."""
    size, rank = comm.size, comm.rank
    recv[...] = send
    if size == 1:
        return
    flat = recv.reshape(-1)
    pof2 = 1 << (size.bit_length() - 1)
    rem = size - pof2
    if rank < 2 * rem:
        if rank % 2 == 0:
            comm.send(flat, rank + 1, T_REDUCE)
            newrank = -1
        else:
            tmp = np.empty_like(flat)
            comm.recv(tmp, rank - 1, T_REDUCE)
            flat[...] = op(tmp, flat)
            newrank = rank // 2
    else:
        newrank = rank - rem

    def block_span(nr: int, down_to_mask: int):
        """Span nr holds after the halving decisions for masks ≥ down_to_mask
        (halving may be uneven when the vector doesn't split in two exactly,
        so spans must be recomputed per rank, never assumed equal)."""
        blo, bhi = 0, flat.size
        m = pof2 >> 1
        while m >= down_to_mask:
            mid = blo + (bhi - blo) // 2
            if nr & m:
                blo = mid
            else:
                bhi = mid
            m >>= 1
        return blo, bhi

    if newrank >= 0:
        # recursive halving reduce-scatter over pof2 ranks
        mask = pof2 >> 1
        lo, hi = 0, flat.size
        while mask > 0:
            peer_new = newrank ^ mask
            peer = peer_new * 2 + 1 if peer_new < rem else peer_new + rem
            mid = lo + (hi - lo) // 2
            if newrank & mask:
                keep_lo, keep_hi = mid, hi
                send_lo, send_hi = lo, mid
            else:
                keep_lo, keep_hi = lo, mid
                send_lo, send_hi = mid, hi
            inbox = np.empty(keep_hi - keep_lo, flat.dtype)
            comm.sendrecv(flat[send_lo:send_hi], peer, inbox, peer,
                          T_RSCAT, T_RSCAT)
            seg = flat[keep_lo:keep_hi]
            if op.commutative or peer < rank:
                seg[...] = op(inbox, seg)
            else:
                seg[...] = op(seg.copy(), inbox)
            lo, hi = keep_lo, keep_hi
            mask >>= 1
        # recursive doubling allgather, retracing in reverse; the peer's
        # current span is its own halving-path block, which can differ from
        # ours by one element per level on non-power-of-two vector sizes
        mask = 1
        while mask < pof2:
            peer_new = newrank ^ mask
            peer = peer_new * 2 + 1 if peer_new < rem else peer_new + rem
            plo, phi = block_span(peer_new, mask)
            inbox = np.empty(phi - plo, flat.dtype)
            comm.sendrecv(flat[lo:hi], peer, inbox, peer,
                          T_ALLGATHER, T_ALLGATHER)
            flat[plo:phi] = inbox
            lo, hi = min(lo, plo), max(hi, phi)
            mask <<= 1
    if rank < 2 * rem:
        if rank % 2 == 0:
            comm.recv(flat, rank + 1, T_BCAST)
        else:
            comm.send(flat, rank - 1, T_BCAST)


def allreduce_segmented_ring(comm, send: np.ndarray, recv: np.ndarray,
                             op: Op, segsize: int) -> None:
    """coll_base_allreduce.c:621 — ring reduce-scatter+allgather where each
    per-step chunk transfer is pipelined in ``segsize``-byte segments: the
    next segment's sendrecv is posted (isend+irecv) before the current
    segment's reduction runs, overlapping wire time with compute. This is
    the segmented/pipelined discipline the whole coll/base library applies
    to large messages (segsize parameters throughout, SURVEY.md §5.7)."""
    size, rank = comm.size, comm.rank
    recv[...] = send
    if size == 1:
        return
    flat = recv.reshape(-1)
    seg_items = max(1, segsize // flat.dtype.itemsize)
    bounds = _ring_bounds(flat.size, size)
    right, left = (rank + 1) % size, (rank - 1) % size

    def spans(chunk):
        lo, hi = int(bounds[chunk]), int(bounds[chunk + 1])
        return [(s, min(s + seg_items, hi)) for s in range(lo, hi, seg_items)] \
            or [(lo, lo)]

    # reduce-scatter phase, depth-2 pipelined per chunk
    for step in range(size - 1):
        s_spans = spans((rank - step) % size)
        r_spans = spans((rank - step - 1) % size)
        n = max(len(s_spans), len(r_spans))
        inboxes = [np.empty(b - a, flat.dtype) for a, b in r_spans]
        sreqs, rreqs = {}, {}

        def post(j):
            if j < len(r_spans):
                rreqs[j] = comm.irecv(inboxes[j], left, T_REDUCE)
            if j < len(s_spans):
                a, b = s_spans[j]
                sreqs[j] = comm.isend(flat[a:b], right, T_REDUCE)

        post(0)
        for j in range(n):
            post(j + 1)             # next segment in flight…
            if j in rreqs:
                rreqs[j].wait()     # …while this one reduces
                a, b = r_spans[j]
                seg = flat[a:b]
                seg[...] = op(inboxes[j], seg)
            if j in sreqs:
                sreqs[j].wait()
    # allgather phase: pure copy — single-segment pipelining gains nothing
    _ring_allgather_phase(comm, flat, bounds, T_ALLGATHER)


# ---------------------------------------------------------------------------
# bcast / reduce trees
# ---------------------------------------------------------------------------

def _binomial_children(rank: int, size: int, root: int):
    """Binomial tree rooted at root (≙ coll_base_topo.c:331 bmtree)."""
    vrank = (rank - root) % size
    children = []
    mask = 1
    while mask < size:
        if vrank & mask:
            parent = ((vrank & ~mask) + root) % size
            return parent, children
        child = vrank | mask
        if child < size:
            children.append((child + root) % size)
        mask <<= 1
    return None, children


def bcast_binomial(comm, buf: np.ndarray, root: int) -> None:
    """coll_base_bcast.c:333."""
    parent, children = _binomial_children(comm.rank, comm.size, root)
    if parent is not None:
        comm.recv(buf, parent, T_BCAST)
    reqs = [comm.isend(buf, c, T_BCAST) for c in reversed(children)]
    wait_all(reqs)


def bcast_scatter_allgather(comm, buf: np.ndarray, root: int) -> None:
    """coll_base_bcast.c:774 — binomial scatter then ring allgather;
    bandwidth-optimal for large messages."""
    size, rank = comm.size, comm.rank
    flat = buf.reshape(-1)
    counts = [len(c) for c in np.array_split(np.arange(flat.size), size)]
    displs = np.concatenate([[0], np.cumsum(counts)[:-1]]).astype(int)
    vrank = (rank - root) % size
    # binomial scatter of segments
    parent, _children = _binomial_children(rank, size, root)
    mask = 1 << max(0, size.bit_length() - 1)
    # receive my subtree's span from parent
    def span(vr, m):
        lo = displs[vr]
        hi_rank = min(size - 1, vr + m - 1)
        hi = displs[hi_rank] + counts[hi_rank]
        return lo, hi
    if parent is not None:
        m = 1
        while not (vrank & m):
            m <<= 1
        lo, hi = span(vrank, m)
        comm.recv(flat[lo:hi], parent, T_BCAST)
    m = 1
    while m < size:
        if vrank & m:
            break
        m <<= 1
    m >>= 1
    while m >= 1:
        vchild = vrank | m
        if vchild < size:
            lo, hi = span(vchild, m)
            comm.send(flat[lo:hi], (vchild + root) % size, T_BCAST)
        m >>= 1
    # ring allgather of segments
    right, left = (rank + 1) % size, (rank - 1) % size
    for step in range(size - 1):
        sv = (vrank - step) % size
        rv = (vrank - step - 1) % size
        s_lo, s_hi = displs[sv], displs[sv] + counts[sv]
        r_lo, r_hi = displs[rv], displs[rv] + counts[rv]
        inbox = np.empty(r_hi - r_lo, flat.dtype)
        comm.sendrecv(flat[s_lo:s_hi], right, inbox, left,
                      T_ALLGATHER, T_ALLGATHER)
        flat[r_lo:r_hi] = inbox


def _segments(flat: np.ndarray, segsize: int):
    seg_items = max(1, segsize // flat.dtype.itemsize)
    return [flat[i:i + seg_items] for i in range(0, flat.size, seg_items)] \
        or [flat]


def bcast_pipeline(comm, buf: np.ndarray, root: int, segsize: int,
                   chains: int = 1) -> None:
    """coll_base_bcast.c:277 (pipeline) / :305 (chain): non-root ranks form
    ``chains`` chains hanging off the root; the message streams down each
    chain in segsize segments, every rank forwarding segment j to its child
    while segment j+1 is still arriving (all receives pre-posted). pipeline
    = chain with chains=1."""
    size, rank = comm.size, comm.rank
    if size == 1:
        return
    flat = buf.reshape(-1)
    segs = _segments(flat, segsize)
    chains = max(1, min(chains, size - 1))
    clen = -(-(size - 1) // chains)          # ceil chain length
    if rank == root:
        heads = [(root + 1 + c * clen) % size
                 for c in range(chains) if c * clen < size - 1]
        sreqs = []
        for s in segs:
            for h in heads:
                sreqs.append(comm.isend(s, h, T_BCAST))
        wait_all(sreqs)
        return
    idx = (rank - root) % size - 1           # position among non-root ranks
    pos = idx % clen
    parent = root if pos == 0 else (rank - 1 + size) % size
    nxt = idx + 1
    child = None
    if pos + 1 < clen and nxt < size - 1:
        child = (rank + 1) % size
    rreqs = [comm.irecv(s, parent, T_BCAST) for s in segs]
    sreqs = []
    for j, s in enumerate(segs):
        rreqs[j].wait()
        if child is not None:
            sreqs.append(comm.isend(s, child, T_BCAST))
    wait_all(sreqs)


def _knomial_tree(rank: int, size: int, root: int, radix: int):
    """K-nomial tree (≙ coll_base_topo.c:479 kmtree): a vrank's parent
    clears its least-significant nonzero base-radix digit; its children add
    d*mask for every level below that digit."""
    vrank = (rank - root) % size
    children = []
    mask = 1
    parent = None
    while mask < size:
        digit = (vrank // mask) % radix
        if digit:
            parent = ((vrank - digit * mask) + root) % size
            break
        for d in range(1, radix):
            child = vrank + d * mask
            if child < size:
                children.append((child + root) % size)
        mask *= radix
    return parent, children


def bcast_knomial(comm, buf: np.ndarray, root: int, radix: int) -> None:
    """coll_base_bcast.c:720 — radix-k binomial tree: shallower than
    binomial (log_k p rounds) at the cost of k-1 sends per internal node;
    wins for small messages where latency dominates."""
    parent, children = _knomial_tree(comm.rank, comm.size, root,
                                     max(2, radix))
    if parent is not None:
        comm.recv(buf, parent, T_BCAST)
    # farthest (largest-subtree) children first, like the reference
    reqs = [comm.isend(buf, c, T_BCAST) for c in reversed(children)]
    wait_all(reqs)


def reduce_inorder_binary(comm, send: np.ndarray, recv: Optional[np.ndarray],
                          op: Op, root: int) -> Optional[np.ndarray]:
    """coll_base_reduce.c:514 — in-order binary tree for NON-commutative
    ops: the reduction combines rank ranges strictly as
    op(ranks lo..mid-1, ranks mid..hi), so the result equals the canonical
    left-to-right fold regardless of tree shape."""
    rank = comm.rank

    def reduce_range(lo: int, hi: int):
        """Value of fold(lo..hi), landing on rank lo; None elsewhere."""
        if lo == hi:
            return send.copy() if rank == lo else None
        mid = (lo + hi + 1) // 2
        if rank < mid:
            v = reduce_range(lo, mid - 1)
            if rank == lo:
                tmp = np.empty_like(send)
                comm.recv(tmp, mid, T_REDUCE)
                return op(v, tmp)        # left range before right range
            return None
        v = reduce_range(mid, hi)
        if rank == mid:
            comm.send(v, lo, T_REDUCE)
        return None

    acc = reduce_range(0, comm.size - 1)
    if root != 0:                        # relocate the fold to the root
        if rank == 0:
            comm.send(acc, root, T_REDUCE)
            return None
        if rank == root:
            acc = np.empty_like(send)
            comm.recv(acc, 0, T_REDUCE)
    if rank != root:
        return None
    if recv is None:
        recv = np.empty_like(send)
    recv[...] = acc
    return recv


def reduce_binomial(comm, send: np.ndarray, recv: Optional[np.ndarray],
                    op: Op, root: int) -> Optional[np.ndarray]:
    """coll_base_reduce.c:476 — commutative ops only (callers guard)."""
    acc = send.copy()
    rank, size = comm.rank, comm.size
    vrank = (rank - root) % size
    tmp = np.empty_like(acc)
    mask = 1
    while mask < size:
        if vrank & mask:
            parent = ((vrank & ~mask) + root) % size
            comm.send(acc, parent, T_REDUCE)
            return None
        vchild = vrank | mask
        if vchild < size:
            comm.recv(tmp, (vchild + root) % size, T_REDUCE)
            acc = op(tmp, acc)
        mask <<= 1
    if recv is None:
        recv = np.empty_like(send)
    recv[...] = acc
    return recv


def reduce_pipeline(comm, send: np.ndarray, recv: Optional[np.ndarray],
                    op: Op, root: int, segsize: int) -> Optional[np.ndarray]:
    """coll_base_reduce.c:414 — segmented chain toward the root: each rank
    receives its child's partial segment, folds it (own value as the LEFT
    operand, so the fold is associativity-equivalent to the canonical
    order), and forwards — segment k+1 arrives while segment k reduces.
    Like every segmented algorithm, valid for ELEMENTWISE ops only (all
    MPI predefined ops are; whole-buffer user ops go through the in-order
    tree instead)."""
    size, rank = comm.size, comm.rank
    vrank = (rank - root) % size
    acc = np.asarray(send).copy()
    flat = acc.reshape(-1)
    segs = _segments(flat, segsize)
    child = ((vrank + 1) + root) % size if vrank + 1 < size else None
    parent = ((vrank - 1) + root) % size if vrank > 0 else None
    rreqs = []
    if child is not None:
        inboxes = [np.empty_like(s) for s in segs]
        rreqs = [comm.irecv(b, child, T_REDUCE) for b in inboxes]
    sreqs = []
    for j, s in enumerate(segs):
        if child is not None:
            rreqs[j].wait()
            s[...] = op(s.copy(), inboxes[j])   # own left, child right
        if parent is not None:
            sreqs.append(comm.isend(s, parent, T_REDUCE))
    wait_all(sreqs)
    if rank != root:
        return None
    if recv is None:
        recv = np.empty_like(np.asarray(send))
    recv[...] = acc
    return recv


def gather_binomial(comm, send: np.ndarray, recv: Optional[np.ndarray],
                    root: int) -> Optional[np.ndarray]:
    """coll_base_gather.c:41 — binomial tree: each internal node forwards
    its whole contiguous vrank-subtree block in one message (log p rounds,
    vs p-1 messages at the linear root)."""
    size, rank = comm.size, comm.rank
    vrank = (rank - root) % size
    row = np.asarray(send).reshape(-1)
    # scratch = only MY subtree (lowbit(vrank) rows; the root holds all):
    # a leaf allocates 1 row, not O(p·n) (r2 review finding)
    subtree = size if vrank == 0 else min(vrank & -vrank, size - vrank)
    work = np.empty((subtree, row.size), row.dtype)
    work[0] = row
    mask = 1
    while mask < size:
        if vrank & mask:
            parent = ((vrank & ~mask) + root) % size
            comm.send(work[:min(mask, size - vrank)], parent, T_GATHER)
            return None
        vchild = vrank | mask
        if vchild < size:
            cnt = min(mask, size - vchild)
            comm.recv(work[mask:mask + cnt], (vchild + root) % size,
                      T_GATHER)
        mask <<= 1
    if recv is None:
        recv = np.empty((size,) + np.asarray(send).shape, row.dtype)
    out = recv.reshape(size, -1)
    for v in range(size):            # un-rotate vrank order → global ranks
        out[(v + root) % size] = work[v]
    return recv


def scatter_binomial(comm, send: Optional[np.ndarray], recv: np.ndarray,
                     root: int) -> np.ndarray:
    """coll_base_scatter.c:63 — the gather tree reversed: the root peels
    off subtree blocks; each internal node forwards its children's."""
    size, rank = comm.size, comm.rank
    vrank = (rank - root) % size
    recv = np.asarray(recv)
    blk = recv.reshape(-1).size
    if vrank == 0:
        parts = np.asarray(send).reshape(size, -1)
        work = np.empty((size, blk), parts.dtype)
        for g in range(size):        # rotate global ranks → vrank order
            work[(g - root) % size] = parts[g]
    else:
        # my subtree block arrives from the parent in one message
        sub = 1
        while not (vrank & sub):
            sub <<= 1
        cnt = min(sub, size - vrank)
        work = np.empty((cnt, blk), recv.dtype)
        parent = ((vrank & ~sub) + root) % size
        comm.recv(work, parent, T_SCATTER)
    mask = 1
    while mask < size and not (vrank & mask):
        mask <<= 1
    m = mask >> 1
    while m >= 1:                    # forward sub-blocks, farthest first
        vchild = vrank | m
        if vchild < size:
            cnt = min(m, size - vchild)
            comm.send(np.ascontiguousarray(work[m:m + cnt]),
                      (vchild + root) % size, T_SCATTER)
        m >>= 1
    recv.reshape(-1)[:] = work[0]
    return recv


def barrier_double_ring(comm) -> None:
    """coll_base_barrier.c:116 — the token circles twice; 2p messages but
    only nearest-neighbor links (the topology-friendliest barrier)."""
    size, rank = comm.size, comm.rank
    token = np.zeros(0, np.uint8)
    right, left = (rank + 1) % size, (rank - 1) % size
    for _round in range(2):
        if rank == 0:
            comm.send(token, right, T_BARRIER)
            comm.recv(token, left, T_BARRIER)
        else:
            comm.recv(token, left, T_BARRIER)
            comm.send(token, right, T_BARRIER)


def allgatherv_ring(comm, send: np.ndarray, recv: np.ndarray,
                    counts: Sequence[int], displs: Sequence[int]) -> None:
    """coll_base_allgatherv.c:371 — the ring schedule with per-rank block
    sizes; p-1 neighbor exchanges instead of the basic component's p-1
    point-to-point pairs per rank."""
    size, rank = comm.size, comm.rank
    flat = recv.reshape(-1)
    flat[displs[rank]:displs[rank] + counts[rank]] = \
        np.asarray(send).reshape(-1)
    right, left = (rank + 1) % size, (rank - 1) % size
    for step in range(size - 1):
        s = (rank - step) % size
        d = (rank - step - 1) % size
        inbox = np.empty(counts[d], flat.dtype)
        comm.sendrecv(flat[displs[s]:displs[s] + counts[s]], right,
                      inbox, left, T_ALLGATHER, T_ALLGATHER)
        flat[displs[d]:displs[d] + counts[d]] = inbox


# ---------------------------------------------------------------------------
# allgather / alltoall / reduce_scatter / barrier
# ---------------------------------------------------------------------------

def allgather_recursive_doubling(comm, send: np.ndarray,
                                 recv: np.ndarray) -> None:
    """coll_base_allgather.c:85 — power-of-2 comms."""
    size, rank = comm.size, comm.rank
    parts = recv.reshape(size, -1)
    parts[rank] = send.reshape(-1)
    mask = 1
    while mask < size:
        peer = rank ^ mask
        block = (rank // mask) * mask
        peer_block = (peer // mask) * mask
        outbox = parts[block:block + mask]
        inbox = np.empty_like(parts[peer_block:peer_block + mask])
        comm.sendrecv(outbox, peer, inbox, peer, T_ALLGATHER, T_ALLGATHER)
        parts[peer_block:peer_block + mask] = inbox
        mask <<= 1


def allgather_ring(comm, send: np.ndarray, recv: np.ndarray) -> None:
    """coll_base_allgather.c:330 — the uniform-counts case of the ring
    schedule (one implementation, see allgatherv_ring)."""
    n = recv.reshape(comm.size, -1).shape[1]
    allgatherv_ring(comm, send, recv, [n] * comm.size,
                    [i * n for i in range(comm.size)])


def allgather_bruck(comm, send: np.ndarray, recv: np.ndarray) -> None:
    """coll_base_allgather.c:767 (k=2 Bruck): log2(p) rounds, any p."""
    size, rank = comm.size, comm.rank
    parts = recv.reshape(size, -1)
    # local rotation: my block first
    work = np.empty_like(parts)
    work[0] = send.reshape(-1)
    have = 1
    dist = 1
    while dist < size:
        peer_to = (rank - dist) % size
        peer_from = (rank + dist) % size
        blkcount = min(have, size - have)
        inbox = np.empty((blkcount, parts.shape[1]), parts.dtype)
        comm.sendrecv(work[:blkcount], peer_to, inbox, peer_from,
                      T_ALLGATHER, T_ALLGATHER)
        work[have:have + blkcount] = inbox[:min(blkcount, size - have)]
        have += blkcount
        dist <<= 1
    # un-rotate: work[i] holds block (rank + i) mod size
    for i in range(size):
        parts[(rank + i) % size] = work[i]


def alltoall_pairwise(comm, send: np.ndarray, recv: np.ndarray) -> None:
    """coll_base_alltoall.c:180 — p-1 exchange rounds with xor/offset pairing."""
    size, rank = comm.size, comm.rank
    sp = send.reshape(size, -1)
    rp = recv.reshape(size, -1)
    rp[rank] = sp[rank]
    for step in range(1, size):
        sendto = (rank + step) % size
        recvfrom = (rank - step) % size
        comm.sendrecv(sp[sendto], sendto, rp[recvfrom], recvfrom,
                      T_ALLTOALL, T_ALLTOALL)


def alltoall_bruck(comm, send: np.ndarray, recv: np.ndarray) -> None:
    """coll_base_alltoall.c:239 — log2(p) rounds for small messages."""
    size, rank = comm.size, comm.rank
    sp = send.reshape(size, -1)
    # phase 1: local rotation so block i is for rank (rank+i)%size
    work = np.roll(sp, -rank, axis=0).copy()
    pof = 1
    while pof < size:
        mask_blocks = [i for i in range(size) if i & pof]
        outbox = work[mask_blocks].copy()
        inbox = np.empty_like(outbox)
        comm.sendrecv(outbox, (rank + pof) % size, inbox, (rank - pof) % size,
                      T_ALLTOALL, T_ALLTOALL)
        work[mask_blocks] = inbox
        pof <<= 1
    # phase 3: inverse rotation + reversal
    rp = recv.reshape(size, -1)
    for i in range(size):
        rp[(rank - i) % size] = work[i]


def reduce_scatter_block_recursive_halving(comm, send: np.ndarray,
                                           recv: np.ndarray, op: Op) -> None:
    """coll_base_reduce_scatter.c:132 adapted to equal blocks (pof2 only)."""
    size, rank = comm.size, comm.rank
    flat = send.reshape(-1).copy()
    blk = flat.size // size
    lo, hi = 0, flat.size
    mask = size >> 1
    while mask > 0:
        peer = rank ^ mask
        mid = lo + (hi - lo) // 2
        if rank & mask:
            keep_lo, keep_hi, send_lo, send_hi = mid, hi, lo, mid
        else:
            keep_lo, keep_hi, send_lo, send_hi = lo, mid, mid, hi
        inbox = np.empty(keep_hi - keep_lo, flat.dtype)
        comm.sendrecv(flat[send_lo:send_hi], peer, inbox, peer,
                      T_RSCAT, T_RSCAT)
        seg = flat[keep_lo:keep_hi]
        if op.commutative or peer < rank:
            seg[...] = op(inbox, seg)
        else:
            seg[...] = op(seg.copy(), inbox)
        lo, hi = keep_lo, keep_hi
        mask >>= 1
    recv.reshape(-1)[:] = flat[rank * blk:(rank + 1) * blk]


def allgather_neighbor_exchange(comm, send: np.ndarray,
                                recv: np.ndarray) -> None:
    """coll_base_allgather.c:456 — even comm sizes: p/2 rounds alternating
    between the two ring neighbors; each round forwards the pair of blocks
    learned in the previous round. Half the rounds of ring for the same
    per-round payload shape."""
    size, rank = comm.size, comm.rank
    parts = recv.reshape(size, -1)
    parts[rank] = send.reshape(-1)
    sched = _neighbor_exchange_schedule(size)[rank]
    for peer, send_blocks, recv_blocks in sched:
        outbox = parts[send_blocks].copy()
        inbox = np.empty((len(recv_blocks), parts.shape[1]), parts.dtype)
        comm.sendrecv(outbox, peer, inbox, peer, T_ALLGATHER, T_ALLGATHER)
        parts[recv_blocks] = inbox


_NE_SCHED_CACHE: dict = {}


def _neighbor_exchange_schedule(size: int):
    """Per-rank [(peer, send_block_ids, recv_block_ids)] for the
    neighbor-exchange rounds; deterministic, cached per comm size."""
    sched = _NE_SCHED_CACHE.get(size)
    if sched is not None:
        return sched
    recent = {r: [r] for r in range(size)}
    sched = {r: [] for r in range(size)}
    for step in range(size // 2):
        peers = {}
        for r in range(size):
            if (r % 2 == 0) == (step % 2 == 0):
                peers[r] = (r + 1) % size
            else:
                peers[r] = (r - 1) % size
        nxt = {}
        for r in range(size):
            p = peers[r]
            sched[r].append((p, list(recent[r]), list(recent[p])))
            nxt[r] = [r, p] if step == 0 else list(recent[p])
        recent = nxt
    _NE_SCHED_CACHE[size] = sched
    return sched


def reduce_scatter_butterfly(comm, send: np.ndarray, recv: np.ndarray,
                             counts: Sequence[int], displs: Sequence[int],
                             op: Op) -> None:
    """coll_base_reduce_scatter.c:691 — butterfly for ANY comm size and
    arbitrary per-rank counts: non-power-of-two remainders fold their full
    vector into a partner first, the 2^k survivors run recursive vector
    halving along original-block boundaries, then folded-out ranks get
    their block back."""
    size, rank = comm.size, comm.rank
    flat = np.asarray(send).reshape(-1).astype(send.dtype, copy=True)
    total = flat.size
    pof2 = 1 << (size.bit_length() - 1)
    rem = size - pof2
    myview = recv.reshape(-1)
    if rank < 2 * rem:
        if rank % 2 == 0:           # folds out; receives its block at the end
            comm.send(flat, rank + 1, T_RSCAT)
            comm.recv(myview, rank + 1, T_RSCAT)
            return
        tmp = np.empty_like(flat)
        comm.recv(tmp, rank - 1, T_RSCAT)
        flat[...] = op(tmp, flat)
        newrank = rank // 2
    else:
        newrank = rank - rem

    def start_block(nr: int) -> int:      # first original block nr represents
        return 2 * nr if nr < rem else nr + rem

    def bound(g: int) -> int:             # element offset of group boundary g
        return total if g >= pof2 else int(displs[start_block(g)])

    glo, ghi = 0, pof2
    mask = pof2 >> 1
    while mask > 0:
        peer_new = newrank ^ mask
        peer = peer_new * 2 + 1 if peer_new < rem else peer_new + rem
        gmid = glo + mask
        if newrank & mask:
            keep = (gmid, ghi)
            send_rng = (glo, gmid)
        else:
            keep = (glo, gmid)
            send_rng = (gmid, ghi)
        k_lo, k_hi = bound(keep[0]), bound(keep[1])
        s_lo, s_hi = bound(send_rng[0]), bound(send_rng[1])
        inbox = np.empty(k_hi - k_lo, flat.dtype)
        comm.sendrecv(flat[s_lo:s_hi], peer, inbox, peer, T_RSCAT, T_RSCAT)
        seg = flat[k_lo:k_hi]
        seg[...] = op(inbox, seg)
        glo, ghi = keep
        mask >>= 1
    # newrank now holds the reduced segment for its original block(s)
    b0 = start_block(newrank)
    if newrank < rem:                     # deliver the even partner's block
        comm.send(flat[displs[b0]:displs[b0] + counts[b0]], rank - 1, T_RSCAT)
        b0 += 1
    myview[:] = flat[displs[b0]:displs[b0] + counts[b0]]


def reduce_scatter_block_butterfly(comm, send: np.ndarray,
                                   recv: np.ndarray, op: Op) -> None:
    """coll_base_reduce_scatter.c:691, equal-block case (see
    reduce_scatter_butterfly for the general-counts engine)."""
    size = comm.size
    blk = np.asarray(send).reshape(-1).size // size
    reduce_scatter_butterfly(comm, send, recv, [blk] * size,
                             [i * blk for i in range(size)], op)


def barrier_recursive_doubling(comm) -> None:
    """coll_base_barrier.c:188; bruck (:269) handles non-pof2 the same way
    here because sendrecv pairs are symmetric per round."""
    size, rank = comm.size, comm.rank
    token = np.zeros(0, np.uint8)
    mask = 1
    while mask < size:
        to = (rank + mask) % size
        frm = (rank - mask) % size
        comm.sendrecv(token, to, token, frm, T_BARRIER, T_BARRIER)
        mask <<= 1


def scan_recursive_doubling(comm, send: np.ndarray, recv: np.ndarray,
                            op: Op, exclusive: bool) -> None:
    """coll_base_scan.c:157 — log2(p) rounds; ok for non-commutative because
    partner ordering is preserved."""
    size, rank = comm.size, comm.rank
    total = send.copy()        # running op over my prefix window
    have_prefix = False
    prefix = np.empty_like(send)
    tmp = np.empty_like(send)
    mask = 1
    while mask < size:
        lo_peer = rank - mask
        hi_peer = rank + mask
        reqs = []
        if hi_peer < size:
            reqs.append(comm.isend(total, hi_peer, T_SCAN))
        if lo_peer >= 0:
            comm.recv(tmp, lo_peer, T_SCAN)
            if have_prefix:
                prefix[...] = op(tmp, prefix)
            else:
                prefix[...] = tmp
                have_prefix = True
            total = op(tmp.copy(), total)
        wait_all(reqs)
        mask <<= 1
    if exclusive:
        if have_prefix:
            recv[...] = prefix
    else:
        recv[...] = op(prefix, send.copy()) if have_prefix else send


# ---------------------------------------------------------------------------
# block-exchange schedule engine (shared by sparbit / bruck / k-bruck /
# neighbor-exchange allgather[v] variants)
# ---------------------------------------------------------------------------

import threading
from collections import OrderedDict

_BLOCK_SCHED_CACHE: OrderedDict = OrderedDict()
_BLOCK_SCHED_CACHE_MAX = 32   # LRU bound — see scaling note below
# run_ranks ranks are threads in one process, and they all hit the cache
# during block-exchange collectives — the LRU reorder/evict pair must not
# race (a concurrent evict between get and move_to_end would KeyError)
_BLOCK_SCHED_LOCK = threading.Lock()


def _block_schedule(size: int, distances: tuple, radix: int):
    """Precompute a deterministic block-exchange schedule: in the round for
    distance d, every rank sends to (rank - j*d) % size for j in 1..radix-1
    all blocks it holds that the receiver neither holds nor has been
    promised earlier this round, and symmetrically receives from
    (rank + j*d).  Built by simulating all ranks at once, so both endpoints
    of every message agree on its block list (and size) by construction —
    the same determinism argument as the neighbor-exchange schedule.

    Distance-halving distances give sparbit (coll_base_allgather.c:227),
    distance-doubling gives Bruck without the final rotation (:767 /
    allgatherv :95) — blocks travel addressed by their ORIGINAL indices, so
    no rotation pass is needed and per-rank counts may vary freely.

    Scaling: simulating all ranks costs O(p²·log p·radix) time and O(p²)
    memory per distinct (size, distances, radix) — fine for TPU-host comm
    sizes (tens of ranks); the decision tables route very large comms to
    ring/recursive-doubling variants first. The cache is a small LRU so
    many distinct comm sizes in one job cannot accumulate unboundedly."""
    key = (size, distances, radix)
    with _BLOCK_SCHED_LOCK:
        cached = _BLOCK_SCHED_CACHE.get(key)
        if cached is not None:
            _BLOCK_SCHED_CACHE.move_to_end(key)
            return cached
    have = {r: {r} for r in range(size)}
    order = {r: [r] for r in range(size)}   # deterministic block ordering
    rounds = {r: [] for r in range(size)}
    for d in distances:
        snap_order = {r: list(order[r]) for r in range(size)}
        snap_have = {r: set(have[r]) for r in range(size)}
        promised = {r: set() for r in range(size)}
        entry = {r: ([], []) for r in range(size)}
        for j in range(1, radix):
            for r in range(size):
                frm = (r + j * d) % size
                if frm == r:
                    continue
                rb = [b for b in snap_order[frm]
                      if b not in snap_have[r] and b not in promised[r]]
                if not rb:
                    continue
                promised[r].update(rb)
                entry[r][1].append((frm, rb))     # my recv
                entry[frm][0].append((r, rb))     # the matching send
        for r in range(size):
            rounds[r].append(entry[r])
            for _frm, rb in entry[r][1]:
                for b in rb:
                    have[r].add(b)
                    order[r].append(b)
    assert all(len(have[r]) == size for r in range(size)), \
        "block schedule incomplete"
    with _BLOCK_SCHED_LOCK:
        _BLOCK_SCHED_CACHE[key] = rounds
        while len(_BLOCK_SCHED_CACHE) > _BLOCK_SCHED_CACHE_MAX:
            _BLOCK_SCHED_CACHE.popitem(last=False)
    return rounds


def _run_block_schedule(comm, rounds, get, tag) -> None:
    """Execute one rank's schedule; ``get(b)`` returns the (already-sized)
    destination view for block b — sends concatenate current views, recvs
    scatter back into them."""
    for sends, recvs in rounds:
        rinfo = []
        for frm, blocks in recvs:
            views = [get(b).reshape(-1) for b in blocks]
            inbox = np.empty(int(sum(v.size for v in views)),
                             views[0].dtype)
            rinfo.append((comm.irecv(inbox, frm, tag), views, inbox))
        sreqs = []
        for to, blocks in sends:
            out = get(blocks[0]).reshape(-1) if len(blocks) == 1 else \
                np.concatenate([get(b).reshape(-1) for b in blocks])
            sreqs.append(comm.isend(out, to, tag))
        for req, views, inbox in rinfo:
            req.wait()
            off = 0
            for v in views:
                v[...] = inbox[off:off + v.size]
                off += v.size
        wait_all(sreqs)


def _doubling_distances(size: int, radix: int = 2) -> tuple:
    d, out = 1, []
    while d < size:
        out.append(d)
        d *= radix
    return tuple(out)


def allgather_sparbit(comm, send: np.ndarray, recv: np.ndarray) -> None:
    """coll_base_allgather.c:227 — sparbit: distance-HALVING block
    exchanges, ceil(log2 p) rounds for any p, no Bruck-style final
    rotation (blocks are addressed by their original indices)."""
    size, rank = comm.size, comm.rank
    parts = recv.reshape(size, -1)
    parts[rank] = send.reshape(-1)
    dists = tuple(reversed(_doubling_distances(size)))
    rounds = _block_schedule(size, dists, 2)[rank]
    _run_block_schedule(comm, rounds, lambda b: parts[b], T_ALLGATHER)


def allgather_kbruck(comm, send: np.ndarray, recv: np.ndarray,
                     radix: int) -> None:
    """coll_base_allgather.c:767 — radix-k Bruck: ceil(log_k p) rounds,
    up to k-1 peers per round (distance-doubling in base k); shallower
    than k=2 when latency dominates and ports allow concurrent sends."""
    size, rank = comm.size, comm.rank
    radix = max(2, radix)
    parts = recv.reshape(size, -1)
    parts[rank] = send.reshape(-1)
    rounds = _block_schedule(size, _doubling_distances(size, radix),
                             radix)[rank]
    _run_block_schedule(comm, rounds, lambda b: parts[b], T_ALLGATHER)


def allgather_two_procs(comm, send: np.ndarray, recv: np.ndarray) -> None:
    """coll_base_allgather.c:570 — the 2-rank special case: one sendrecv."""
    rank = comm.rank
    parts = recv.reshape(2, -1)
    parts[rank] = send.reshape(-1)
    peer = 1 - rank
    comm.sendrecv(parts[rank], peer, parts[peer], peer,
                  T_ALLGATHER, T_ALLGATHER)


def allgather_direct(comm, send: np.ndarray, recv: np.ndarray) -> None:
    """coll_base_allgather.c:930 — direct messaging: p-1 concurrent
    isend/irecv pairs; one round, maximal port pressure."""
    size, rank = comm.size, comm.rank
    parts = recv.reshape(size, -1)
    parts[rank] = send.reshape(-1)
    reqs = []
    for peer in range(size):
        if peer == rank:
            continue
        reqs.append(comm.irecv(parts[peer], peer, T_ALLGATHER))
        reqs.append(comm.isend(parts[rank], peer, T_ALLGATHER))
    wait_all(reqs)


def _v_accessor(flat: np.ndarray, counts: Sequence[int],
                displs: Sequence[int]):
    return lambda b: flat[int(displs[b]):int(displs[b]) + int(counts[b])]


def allgatherv_bruck(comm, send: np.ndarray, recv: np.ndarray,
                     counts: Sequence[int], displs: Sequence[int]) -> None:
    """coll_base_allgatherv.c:95 — Bruck with per-rank counts; the
    original-index addressing of the schedule engine removes the final
    rotation the reference needs."""
    size, rank = comm.size, comm.rank
    flat = recv.reshape(-1)
    acc = _v_accessor(flat, counts, displs)
    acc(rank)[...] = np.asarray(send).reshape(-1)
    rounds = _block_schedule(size, _doubling_distances(size), 2)[rank]
    _run_block_schedule(comm, rounds, acc, T_ALLGATHER)


def allgatherv_sparbit(comm, send: np.ndarray, recv: np.ndarray,
                       counts: Sequence[int], displs: Sequence[int]) -> None:
    """coll_base_allgatherv.c:259 — sparbit with per-rank counts."""
    size, rank = comm.size, comm.rank
    flat = recv.reshape(-1)
    acc = _v_accessor(flat, counts, displs)
    acc(rank)[...] = np.asarray(send).reshape(-1)
    dists = tuple(reversed(_doubling_distances(size)))
    rounds = _block_schedule(size, dists, 2)[rank]
    _run_block_schedule(comm, rounds, acc, T_ALLGATHER)


def allgatherv_neighbor_exchange(comm, send: np.ndarray, recv: np.ndarray,
                                 counts: Sequence[int],
                                 displs: Sequence[int]) -> None:
    """coll_base_allgatherv.c:498 — even comm sizes (caller guards)."""
    size, rank = comm.size, comm.rank
    flat = recv.reshape(-1)
    acc = _v_accessor(flat, counts, displs)
    acc(rank)[...] = np.asarray(send).reshape(-1)
    rounds = [([(peer, sb)], [(peer, rb)])
              for peer, sb, rb in _neighbor_exchange_schedule(size)[rank]]
    _run_block_schedule(comm, rounds, acc, T_ALLGATHER)


def allgatherv_two_procs(comm, send: np.ndarray, recv: np.ndarray,
                         counts: Sequence[int],
                         displs: Sequence[int]) -> None:
    """coll_base_allgatherv.c:643."""
    rank = comm.rank
    flat = recv.reshape(-1)
    acc = _v_accessor(flat, counts, displs)
    acc(rank)[...] = np.asarray(send).reshape(-1)
    peer = 1 - rank
    comm.sendrecv(acc(rank), peer, acc(peer), peer,
                  T_ALLGATHER, T_ALLGATHER)


# ---------------------------------------------------------------------------
# remaining allreduce / bcast / reduce variants
# ---------------------------------------------------------------------------

def allreduce_nonoverlapping(comm, send: np.ndarray, recv: np.ndarray,
                             op: Op) -> None:
    """coll_base_allreduce.c:57 — reduce to rank 0 then bcast; the plain
    composition the overlapped algorithms are measured against."""
    reduce_binomial(comm, send, recv if comm.rank == 0 else None, op, 0)
    bcast_binomial(comm, recv, 0)


def allreduce_allgather_reduce(comm, send: np.ndarray, recv: np.ndarray,
                               op: Op) -> None:
    """coll_base_allreduce.c:1267 — allgather every contribution then fold
    locally in strict rank order: p·n bytes, but a canonical fold, so valid
    for ANY op including non-commutative ones."""
    size = comm.size
    gath = np.empty((size,) + send.shape, send.dtype)
    allgather_bruck(comm, send, gath)
    acc = gath[0].copy()
    for i in range(1, size):
        acc = op(acc, gath[i])
    recv[...] = acc


def bcast_split_binary(comm, buf: np.ndarray, root: int) -> None:
    """coll_base_bcast.c:361 — split-binary tree: the message is halved;
    each half is binomial-bcast down one of the two subtrees hanging off
    the root, then mirror ranks of the two subtrees swap halves pairwise
    (every rank sends ~n/2 + receives n, vs n down every tree edge)."""
    size, rank = comm.size, comm.rank
    flat = buf.reshape(-1)
    if size <= 3 or flat.size < 2:
        return bcast_binomial(comm, buf, root)
    mid = flat.size // 2
    halves = (flat[:mid], flat[mid:])
    vrank = (rank - root) % size
    nL = size // 2                      # |left group| ≥ |right group|
    grp = [list(range(1, nL + 1)), list(range(nL + 1, size))]

    def gmap(side: int, idx: int) -> int:
        return (grp[side][idx] + root) % size

    if vrank == 0:
        reqs = [comm.isend(halves[s], gmap(s, 0), T_BCAST)
                for s in (0, 1) if grp[s]]
        wait_all(reqs)
        return
    side = 0 if vrank <= nL else 1
    idx = vrank - 1 if side == 0 else vrank - 1 - nL
    m = len(grp[side])
    parent, children = _binomial_children(idx, m, 0)
    my = halves[side]
    src = root if parent is None else gmap(side, parent)
    comm.recv(my, src, T_BCAST)
    reqs = [comm.isend(my, gmap(side, c), T_BCAST) for c in reversed(children)]
    other = 1 - side
    mo = len(grp[other])
    if idx < mo:
        partner = gmap(other, idx)
        comm.sendrecv(my, partner, halves[other], partner,
                      T_ALLGATHER, T_ALLGATHER)
    else:
        # |L| = |R|+1: the odd left member gets the other half from the
        # last right-group rank (which serves two left partners)
        comm.recv(halves[other], gmap(other, mo - 1), T_ALLGATHER)
    mL, mR = len(grp[0]), len(grp[1])
    if side == 1 and idx == mR - 1 and mL > mR:
        comm.send(my, gmap(0, mL - 1), T_ALLGATHER)
    wait_all(reqs)


def reduce_chain(comm, send: np.ndarray, recv: Optional[np.ndarray], op: Op,
                 root: int, segsize: int, fanout: int) -> Optional[np.ndarray]:
    """coll_base_reduce.c:384 — ``fanout`` independent segmented chains;
    each chain pipelines partial folds toward its head, heads stream their
    segments to the root, which folds across chains (commutative ops only —
    the dispatcher guards)."""
    size, rank = comm.size, comm.rank
    vrank = (rank - root) % size
    fanout = max(1, min(fanout, size - 1))
    clen = -(-(size - 1) // fanout)
    acc = np.asarray(send).copy()
    flat = acc.reshape(-1)
    segs = _segments(flat, segsize)
    if vrank == 0:
        heads = list(range(0, size - 1, clen))
        inbox = {h: [np.empty_like(s) for s in segs] for h in heads}
        rreqs = {h: [comm.irecv(b, (h + 1 + root) % size, T_REDUCE)
                     for b in inbox[h]] for h in heads}
        for j, s in enumerate(segs):
            for h in heads:
                rreqs[h][j].wait()
                s[...] = op(inbox[h][j], s)
        if recv is None:
            recv = np.empty_like(np.asarray(send))
        recv[...] = acc
        return recv
    idx = vrank - 1
    pos = idx % clen
    parent = root if pos == 0 else (idx - 1 + 1 + root) % size
    child = (idx + 1 + 1 + root) % size \
        if (pos + 1 < clen and idx + 1 < size - 1) else None
    rreqs, inboxes = [], []
    if child is not None:
        inboxes = [np.empty_like(s) for s in segs]
        rreqs = [comm.irecv(b, child, T_REDUCE) for b in inboxes]
    sreqs = []
    for j, s in enumerate(segs):
        if child is not None:
            rreqs[j].wait()
            s[...] = op(inboxes[j], s)
        sreqs.append(comm.isend(s, parent, T_REDUCE))
    wait_all(sreqs)
    return None


def reduce_knomial(comm, send: np.ndarray, recv: Optional[np.ndarray], op: Op,
                   root: int, radix: int) -> Optional[np.ndarray]:
    """coll_base_reduce.c:1166 — radix-k tree reduce: log_k p rounds
    (commutative ops only — the dispatcher guards)."""
    parent, children = _knomial_tree(comm.rank, comm.size, root,
                                     max(2, radix))
    acc = np.asarray(send).copy()
    tmp = np.empty_like(acc)
    for c in reversed(children):
        comm.recv(tmp, c, T_REDUCE)
        acc = op(tmp, acc)
    if parent is not None:
        comm.send(acc, parent, T_REDUCE)
        return None
    if recv is None:
        recv = np.empty_like(np.asarray(send))
    recv[...] = acc
    return recv


def _halving_span(nr: int, down_to_mask: int, n: int, pof2: int):
    """Span held after recursive-halving decisions for masks ≥ down_to_mask
    (spans must be recomputed per rank: halving an odd-length span is
    uneven)."""
    blo, bhi = 0, n
    m = pof2 >> 1
    while m >= down_to_mask:
        mid = blo + (bhi - blo) // 2
        if nr & m:
            blo = mid
        else:
            bhi = mid
        m >>= 1
    return blo, bhi


def reduce_rabenseifner(comm, send: np.ndarray, recv: Optional[np.ndarray],
                        op: Op, root: int) -> Optional[np.ndarray]:
    """coll_base_reduce.c:811 — recursive-halving reduce-scatter followed
    by a binomial gather of the spans onto the pof2 survivor holding
    newrank 0, which forwards the result to the root when different (the
    reference grafts the root into the gather tree; the single extra
    n-byte hop here trades that bookkeeping away). Commutative only."""
    size, rank = comm.size, comm.rank
    flat = np.asarray(send).reshape(-1).copy()
    pof2 = 1 << (size.bit_length() - 1)
    rem = size - pof2
    holder = 1 if rem > 0 else 0         # original rank of newrank 0
    if rank < 2 * rem:
        if rank % 2 == 0:
            comm.send(flat, rank + 1, T_REDUCE)
            newrank = -1
        else:
            tmp = np.empty_like(flat)
            comm.recv(tmp, rank - 1, T_REDUCE)
            flat[...] = op(tmp, flat)
            newrank = rank // 2
    else:
        newrank = rank - rem
    if newrank >= 0:
        mask = pof2 >> 1
        lo, hi = 0, flat.size
        while mask > 0:
            peer_new = newrank ^ mask
            peer = peer_new * 2 + 1 if peer_new < rem else peer_new + rem
            mid = lo + (hi - lo) // 2
            if newrank & mask:
                keep_lo, keep_hi, send_lo, send_hi = mid, hi, lo, mid
            else:
                keep_lo, keep_hi, send_lo, send_hi = lo, mid, mid, hi
            inbox = np.empty(keep_hi - keep_lo, flat.dtype)
            comm.sendrecv(flat[send_lo:send_hi], peer, inbox, peer,
                          T_RSCAT, T_RSCAT)
            seg = flat[keep_lo:keep_hi]
            seg[...] = op(inbox, seg)
            lo, hi = keep_lo, keep_hi
            mask >>= 1
        # binomial gather of spans toward newrank 0
        mask = 1
        while mask < pof2:
            if newrank & mask:
                peer_new = newrank ^ mask
                peer = peer_new * 2 + 1 if peer_new < rem else peer_new + rem
                comm.send(flat[lo:hi], peer, T_GATHER)
                break
            peer_new = newrank | mask
            if peer_new < pof2:
                peer = peer_new * 2 + 1 if peer_new < rem else peer_new + rem
                plo, phi = _halving_span(peer_new, mask, flat.size, pof2)
                comm.recv(flat[plo:phi], peer, T_GATHER)
                lo, hi = min(lo, plo), max(hi, phi)
            mask <<= 1
    if rank == holder and root != holder:
        comm.send(flat, root, T_REDUCE)
    if rank == root:
        if root != holder:
            comm.recv(flat, holder, T_REDUCE)
        if recv is None:
            recv = np.empty_like(np.asarray(send))
        recv.reshape(-1)[:] = flat
        return recv
    return None


# ---------------------------------------------------------------------------
# alltoall[v] variants
# ---------------------------------------------------------------------------

def alltoall_linear_sync(comm, send: np.ndarray, recv: np.ndarray,
                         max_outstanding: int) -> None:
    """coll_base_alltoall.c:378 — linear with a bounded window of
    outstanding isend/irecv pairs: the next peer's pair is posted only as
    an earlier one completes (flow control at large fan-out)."""
    from ..p2p.request import wait_any
    size, rank = comm.size, comm.rank
    sp = send.reshape(size, -1)
    rp = recv.reshape(size, -1)
    rp[rank] = sp[rank]
    window = max(1, max_outstanding)
    pending: list = []
    for step in range(1, size):
        peer = (rank + step) % size
        while len(pending) >= 2 * window:
            pending.pop(wait_any(pending))
        pending.append(comm.irecv(rp[peer], peer, T_ALLTOALL))
        pending.append(comm.isend(sp[peer], peer, T_ALLTOALL))
    wait_all(pending)


def alltoall_two_procs(comm, send: np.ndarray, recv: np.ndarray) -> None:
    """coll_base_alltoall.c:537."""
    rank = comm.rank
    sp = send.reshape(2, -1)
    rp = recv.reshape(2, -1)
    rp[rank] = sp[rank]
    peer = 1 - rank
    comm.sendrecv(sp[peer], peer, rp[peer], peer, T_ALLTOALL, T_ALLTOALL)


def alltoallv_pairwise(comm, sendbuf: np.ndarray, recvbuf: np.ndarray,
                       sendcounts: Sequence[int], recvcounts: Sequence[int],
                       sdispls: Sequence[int],
                       rdispls: Sequence[int]) -> None:
    """coll_base_alltoallv.c:194 — p-1 offset-paired exchange rounds; one
    in-flight message per rank per round instead of the linear variant's
    2(p-1) concurrent requests."""
    size, rank = comm.size, comm.rank
    sflat = np.asarray(sendbuf).reshape(-1)
    rflat = recvbuf.reshape(-1)
    rflat[rdispls[rank]:rdispls[rank] + recvcounts[rank]] = \
        sflat[sdispls[rank]:sdispls[rank] + sendcounts[rank]]
    for step in range(1, size):
        to = (rank + step) % size
        frm = (rank - step) % size
        comm.sendrecv(sflat[sdispls[to]:sdispls[to] + sendcounts[to]], to,
                      rflat[rdispls[frm]:rdispls[frm] + recvcounts[frm]],
                      frm, T_ALLTOALL, T_ALLTOALL)


# ---------------------------------------------------------------------------
# reduce_scatter (per-rank counts) variants
# ---------------------------------------------------------------------------

def reduce_scatter_ring(comm, send: np.ndarray, recv: np.ndarray,
                        counts: Sequence[int], displs: Sequence[int],
                        op: Op) -> None:
    """coll_base_reduce_scatter.c:456 — ring: block b circles from rank
    b+1 around to its owner, accumulating a contribution at every hop;
    bandwidth-optimal, p-1 neighbor rounds (commutative only)."""
    size, rank = comm.size, comm.rank
    flat = np.asarray(send).reshape(-1).copy()
    right, left = (rank + 1) % size, (rank - 1) % size
    for step in range(size - 1):
        s = (rank - step - 1) % size
        d = (rank - step - 2) % size
        inbox = np.empty(int(counts[d]), flat.dtype)
        comm.sendrecv(flat[displs[s]:displs[s] + counts[s]], right,
                      inbox, left, T_RSCAT, T_RSCAT)
        seg = flat[displs[d]:displs[d] + counts[d]]
        seg[...] = op(inbox, seg)
    recv.reshape(-1)[:] = flat[displs[rank]:displs[rank] + counts[rank]]


def reduce_scatter_recursive_halving(comm, send: np.ndarray,
                                     recv: np.ndarray,
                                     counts: Sequence[int],
                                     displs: Sequence[int], op: Op) -> None:
    """coll_base_reduce_scatter.c:132 — power-of-two comms, arbitrary
    counts: vector halving along rank-block boundaries."""
    size, rank = comm.size, comm.rank
    flat = np.asarray(send).reshape(-1).copy()
    total = flat.size

    def bound(b: int) -> int:
        return total if b >= size else int(displs[b])

    lo_b, hi_b = 0, size
    mask = size >> 1
    while mask > 0:
        peer = rank ^ mask
        mid_b = lo_b + (hi_b - lo_b) // 2
        if rank & mask:
            keep, send_rng = (mid_b, hi_b), (lo_b, mid_b)
        else:
            keep, send_rng = (lo_b, mid_b), (mid_b, hi_b)
        inbox = np.empty(bound(keep[1]) - bound(keep[0]), flat.dtype)
        comm.sendrecv(flat[bound(send_rng[0]):bound(send_rng[1])], peer,
                      inbox, peer, T_RSCAT, T_RSCAT)
        seg = flat[bound(keep[0]):bound(keep[1])]
        if op.commutative or peer < rank:
            seg[...] = op(inbox, seg)
        else:
            seg[...] = op(seg.copy(), inbox)
        lo_b, hi_b = keep
        mask >>= 1
    recv.reshape(-1)[:] = flat[displs[rank]:displs[rank] + counts[rank]]


def reduce_scatter_block_recursive_doubling(comm, send: np.ndarray,
                                            recv: np.ndarray, op: Op) -> None:
    """coll_base_reduce_scatter_block.c:197 — power-of-two comms: log p
    xor-paired rounds over a shrinking alive-set of blocks; each round a
    rank ships the alive blocks belonging to its peer's half and folds the
    ones arriving for its own."""
    size, rank = comm.size, comm.rank
    parts = send.reshape(size, -1).copy()
    alive = list(range(size))
    mask = 1
    while mask < size:
        peer = rank ^ mask
        sel = [b for b in alive if (b & mask) == (peer & mask)]
        keep = [b for b in alive if (b & mask) == (rank & mask)]
        inbox = np.empty((len(keep), parts.shape[1]), parts.dtype)
        comm.sendrecv(np.ascontiguousarray(parts[sel]), peer, inbox, peer,
                      T_RSCAT, T_RSCAT)
        if op.commutative or peer < rank:
            parts[keep] = op(inbox, parts[keep])
        else:
            parts[keep] = op(parts[keep].copy(), inbox)
        alive = keep
        mask <<= 1
    recv.reshape(-1)[:] = parts[rank]


# ---------------------------------------------------------------------------
# remaining barrier / gather / scatter variants
# ---------------------------------------------------------------------------

def barrier_tree(comm) -> None:
    """coll_base_barrier.c:427 — binomial gather-up then release-down."""
    rank, size = comm.rank, comm.size
    token = np.zeros(0, np.uint8)
    parent, children = _binomial_children(rank, size, 0)
    for c in children:
        comm.recv(token, c, T_BARRIER)
    if parent is not None:
        comm.send(token, parent, T_BARRIER)
        comm.recv(token, parent, T_BARRIER)
    for c in children:
        comm.send(token, c, T_BARRIER)


def barrier_two_procs(comm) -> None:
    """coll_base_barrier.c:307."""
    token = np.zeros(0, np.uint8)
    peer = 1 - comm.rank
    comm.sendrecv(token, peer, token, peer, T_BARRIER, T_BARRIER)


def gather_linear_sync(comm, send: np.ndarray, recv: Optional[np.ndarray],
                       root: int) -> Optional[np.ndarray]:
    """coll_base_gather.c:208 — root-paced linear gather: each rank sends
    only after the root's zero-byte go-ahead, bounding unexpected-message
    buildup at the root for large payloads."""
    size, rank = comm.size, comm.rank
    token = np.zeros(0, np.uint8)
    if rank != root:
        comm.recv(token, root, T_GATHER)
        comm.send(np.asarray(send), root, T_GATHER)
        return None
    if recv is None:
        recv = np.empty((size,) + np.asarray(send).shape,
                        np.asarray(send).dtype)
    out = recv.reshape(size, -1)
    out[root] = np.asarray(send).reshape(-1)
    for src in range(size):
        if src == root:
            continue
        comm.send(token, src, T_GATHER)
        comm.recv(out[src], src, T_GATHER)
    return recv


def scatter_linear_nb(comm, send: Optional[np.ndarray], recv: np.ndarray,
                      root: int) -> np.ndarray:
    """coll_base_scatter.c:289 — non-blocking linear: the root posts all
    p-1 isends at once instead of serializing them."""
    size, rank = comm.size, comm.rank
    recv = np.asarray(recv)
    if rank == root:
        parts = np.asarray(send).reshape(size, -1)
        reqs = [comm.isend(parts[p], p, T_SCATTER)
                for p in range(size) if p != root]
        recv.reshape(-1)[:] = parts[root]
        wait_all(reqs)
    else:
        comm.recv(recv.reshape(-1), root, T_SCATTER)
    return recv


# ---------------------------------------------------------------------------
# the tuned module: decision rules + dispatch
# ---------------------------------------------------------------------------

_var.register("coll", "tuned", "dynamic_rules", "", type=str, level=4,
              help="Path to a dynamic rules file: lines of "
                   "'<coll> <min_comm_size> <min_bytes> <algorithm>'.")

for _coll, _algs in {
    "allreduce": "recursive_doubling|ring|segmented_ring|rabenseifner"
                 "|nonoverlapping|allgather_reduce",
    "bcast": "binomial|knomial|pipeline|chain|scatter_allgather"
             "|split_binary",
    "reduce": "binomial|inorder_binary|pipeline|chain|knomial|rabenseifner",
    "allgather": "recursive_doubling|ring|neighbor_exchange|bruck|sparbit"
                 "|k_bruck|two_procs|direct|linear",
    "alltoall": "pairwise|bruck|linear_sync|two_procs|linear",
    "alltoallv": "pairwise|linear",
    "reduce_scatter": "nonoverlapping|ring|recursive_halving|butterfly",
    "reduce_scatter_block": "recursive_halving|butterfly"
                            "|recursive_doubling",
    "gather": "binomial|linear|linear_sync",
    "scatter": "binomial|linear|linear_nb",
    "allgatherv": "ring|linear|bruck|sparbit|neighbor_exchange|two_procs",
    "barrier": "recursive_doubling|double_ring|tree|two_procs|bruck",
    "scan": "recursive_doubling|linear",
    "exscan": "recursive_doubling|linear",
}.items():
    _var.register("coll", "tuned", f"{_coll}_algorithm", "", type=str, level=3,
                  help=f"Force the {_coll} algorithm ({_algs}; empty = auto).")

# segmentation / tree-shape knobs (≙ coll_tuned_*_segment_size / radix /
# chains MCA vars). Defaults below come from the recorded host sweep in
# TUNE_SWEEP.json (tools/coll_tune.py), not guesses.
_var.register("coll", "tuned", "allreduce_segsize", 256 << 10, type=int,
              level=4, help="Segment bytes for segmented-ring allreduce.")
_var.register("coll", "tuned", "reduce_segsize", 256 << 10, type=int,
              level=4, help="Segment bytes for pipeline reduce.")
_var.register("coll", "tuned", "bcast_segsize", 128 << 10, type=int,
              level=4, help="Segment bytes for pipeline/chain bcast.")
_var.register("coll", "tuned", "bcast_chains", 4, type=int, level=4,
              help="Number of chains for chain bcast.")
_var.register("coll", "tuned", "bcast_knomial_radix", 4, type=int, level=4,
              help="Radix for knomial bcast.")
_var.register("coll", "tuned", "reduce_knomial_radix", 4, type=int, level=4,
              help="Radix for knomial reduce.")
_var.register("coll", "tuned", "reduce_chain_fanout", 4, type=int, level=4,
              help="Number of chains for chain reduce.")
_var.register("coll", "tuned", "allgather_kbruck_radix", 4, type=int, level=4,
              help="Radix for k-Bruck allgather.")
_var.register("coll", "tuned", "alltoall_sync_requests", 8, type=int, level=4,
              help="Outstanding isend/irecv pairs for linear-sync alltoall.")


def _load_dynamic_rules():
    path = _var.get("coll_tuned_dynamic_rules", "")
    rules = []
    if path and os.path.exists(path):
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                coll, min_comm, min_bytes, alg = line.split()
                rules.append((coll, int(min_comm), int(min_bytes), alg))
    return rules


class TunedModule(CollModule):
    """Per-communicator tuned module; falls back to BasicModule for entry
    points without a tuned algorithm (per-function stacking does the same at
    the framework level; the inner fallback keeps semantics like in-order
    reduction in one place)."""

    def __init__(self, comm) -> None:
        self.basic = BasicModule()
        self._rules = _load_dynamic_rules()

    def _pick(self, coll: str, comm, nbytes: int, default: str) -> str:
        forced = _var.get(f"coll_tuned_{coll}_algorithm", "")
        if forced:
            return forced
        pick = default
        for c, mc, mb, alg in self._rules:
            if c == coll and comm.size >= mc and nbytes >= mb:
                pick = alg
        return pick

    # -- allreduce (decision table ≙ coll_tuned_decision_fixed.c:69-104) ----

    def allreduce(self, comm, sendbuf, recvbuf=None, op: Op = None):
        op = _sum_default(op)
        send = _inplace(sendbuf, recvbuf)
        if recvbuf is None:
            recvbuf = np.empty_like(send)
        if comm.size == 1:
            recvbuf[...] = send
            return recvbuf
        if not op.commutative:
            return self.basic.allreduce(comm, send, recvbuf, op)
        nbytes = send.nbytes
        # thresholds from the recorded sweep (TUNE_SWEEP.json, 4 ranks):
        # rd wins ≤16K (1268µs vs ring 2122µs @16K), ring the mid band
        # (4291µs vs rd 7360µs @256K), segmented ring the largest sizes
        # (19.7ms vs ring 30.7ms @2M); rabenseifner never won on this host
        # but stays selectable for multi-core deployments
        default = ("recursive_doubling" if nbytes <= (1 << 16) else
                   ("ring" if nbytes <= (1 << 20) else "segmented_ring"))
        alg = self._pick("allreduce", comm, nbytes, default)
        if send.size < comm.size and alg not in ("nonoverlapping",
                                                 "allgather_reduce"):
            alg = "recursive_doubling"  # tiny vectors can't be scattered
        if alg == "ring":
            allreduce_ring(comm, send, recvbuf, op)
        elif alg == "segmented_ring":
            allreduce_segmented_ring(
                comm, send, recvbuf, op,
                int(_var.get("coll_tuned_allreduce_segsize", 256 << 10)))
        elif alg == "rabenseifner":
            allreduce_rabenseifner(comm, send, recvbuf, op)
        elif alg == "nonoverlapping":
            allreduce_nonoverlapping(comm, send, recvbuf, op)
        elif alg == "allgather_reduce":
            allreduce_allgather_reduce(comm, send, recvbuf, op)
        else:
            allreduce_recursive_doubling(comm, send, recvbuf, op)
        return recvbuf

    def bcast(self, comm, buf, root: int = 0):
        buf = np.asarray(buf)
        if comm.size == 1:
            return buf
        nbytes = buf.nbytes
        # sweep-driven (TUNE_SWEEP.json, 4 ranks): knomial wins the latency
        # regime on the full-library sweep (shallower tree, no segment
        # bookkeeping); pipeline keeps the bandwidth regime — its wire/
        # compute overlap cannot show on the 1-core sweep box (where
        # knomial also "wins" large) but is the structural choice once
        # ranks own cores
        default = "knomial" if nbytes <= (1 << 13) else "pipeline"
        alg = self._pick("bcast", comm, nbytes, default)
        if alg == "scatter_allgather" and buf.size >= comm.size:
            bcast_scatter_allgather(comm, buf, root)
        elif alg == "split_binary":
            bcast_split_binary(comm, buf, root)
        elif alg in ("pipeline", "chain"):
            bcast_pipeline(
                comm, buf, root,
                int(_var.get("coll_tuned_bcast_segsize", 128 << 10)),
                chains=1 if alg == "pipeline"
                else int(_var.get("coll_tuned_bcast_chains", 4)))
        elif alg == "knomial":
            bcast_knomial(comm, buf, root,
                          int(_var.get("coll_tuned_bcast_knomial_radix", 4)))
        else:
            bcast_binomial(comm, buf, root)
        return buf

    def reduce(self, comm, sendbuf, recvbuf=None, op: Op = None, root: int = 0):
        op = _sum_default(op)
        send = _inplace(sendbuf, recvbuf)
        if comm.size == 1:
            if recvbuf is None:
                recvbuf = np.empty_like(send)
            recvbuf[...] = send
            return recvbuf
        if not op.commutative:
            # in-order binary tree keeps the canonical fold order at
            # log(p) depth (vs the linear gather fallback)
            return reduce_inorder_binary(comm, send, recvbuf, op, root)
        # sweep (TUNE_SWEEP.json, 4 ranks, ONE core): knomial wins small
        # (shallow tree), binomial the middle, in-order binary the large
        # regime (balanced log-depth tree with one fold per node — valid
        # for commutative ops too, and the recorded winner ≥256K); the
        # pipeline/chain overlap needs ranks on their own cores to pay
        # off, so they stay selectable, not default
        default = ("knomial" if send.nbytes <= (1 << 11) else
                   ("binomial" if send.nbytes <= (1 << 17)
                    else "inorder_binary"))
        alg = self._pick("reduce", comm, send.nbytes, default)
        if alg == "inorder_binary":
            return reduce_inorder_binary(comm, send, recvbuf, op, root)
        if alg == "pipeline":
            return reduce_pipeline(
                comm, send, recvbuf, op, root,
                int(_var.get("coll_tuned_reduce_segsize", 256 << 10)))
        if alg == "chain":
            return reduce_chain(
                comm, send, recvbuf, op, root,
                int(_var.get("coll_tuned_reduce_segsize", 256 << 10)),
                int(_var.get("coll_tuned_reduce_chain_fanout", 4)))
        if alg == "knomial":
            return reduce_knomial(
                comm, send, recvbuf, op, root,
                int(_var.get("coll_tuned_reduce_knomial_radix", 4)))
        if alg == "rabenseifner":
            return reduce_rabenseifner(comm, send, recvbuf, op, root)
        return reduce_binomial(comm, send, recvbuf, op, root)

    def gather(self, comm, sendbuf, recvbuf=None, root: int = 0):
        if comm.size == 1:
            return self.basic.gather(comm, sendbuf, recvbuf, root)
        # sweep: binomial wins the latency regime, linear the bandwidth one
        # (interior nodes re-forward subtree data the linear root receives
        # once)
        alg = self._pick("gather", comm, np.asarray(sendbuf).nbytes,
                         "binomial" if np.asarray(sendbuf).nbytes <= (1 << 13)
                         else "linear")
        if alg == "linear":
            return self.basic.gather(comm, sendbuf, recvbuf, root)
        if alg == "linear_sync":
            return gather_linear_sync(comm, np.asarray(sendbuf), recvbuf,
                                      root)
        return gather_binomial(comm, np.asarray(sendbuf), recvbuf, root)

    def scatter(self, comm, sendbuf, recvbuf=None, root: int = 0):
        if comm.size == 1:
            return self.basic.scatter(comm, sendbuf, recvbuf, root)
        if recvbuf is None:
            if comm.rank != root:
                raise ValueError("non-root scatter needs recvbuf")
            sb = np.asarray(sendbuf)
            recvbuf = np.empty(sb.reshape((comm.size, -1)).shape[1:],
                               sb.dtype)
        # sweep: linear won at every size on 4 ranks (forwarding doubles
        # interior bytes); binomial stays selectable for large rank counts
        # where the root's p-1 sends become the bottleneck
        alg = self._pick("scatter", comm,
                         np.asarray(recvbuf).nbytes, "linear")
        if alg == "binomial":
            return scatter_binomial(comm, sendbuf, recvbuf, root)
        if alg == "linear_nb":
            return scatter_linear_nb(comm, sendbuf, recvbuf, root)
        return self.basic.scatter(comm, sendbuf, recvbuf, root)

    def allgatherv(self, comm, sendbuf, recvbuf=None, counts=None,
                   displs=None):
        if counts is None or comm.size == 1:
            return self.basic.allgatherv(comm, sendbuf, recvbuf, counts,
                                         displs)
        nbytes = int(np.sum(counts)) * np.asarray(sendbuf).dtype.itemsize
        alg = self._pick("allgatherv", comm, nbytes, "ring")
        if alg == "linear":
            return self.basic.allgatherv(comm, sendbuf, recvbuf, counts,
                                         displs)
        if displs is None:
            displs = list(np.concatenate([[0], np.cumsum(counts)[:-1]]))
        if recvbuf is None:
            # size by the furthest write, not sum(counts): user displs may
            # leave gaps (same contract as the basic module)
            total = max(int(d) + int(c) for d, c in zip(displs, counts))
            recvbuf = np.empty(total, np.asarray(sendbuf).dtype)
        if alg == "bruck":
            allgatherv_bruck(comm, np.asarray(sendbuf), recvbuf, counts,
                             displs)
        elif alg == "sparbit":
            allgatherv_sparbit(comm, np.asarray(sendbuf), recvbuf, counts,
                               displs)
        elif alg == "neighbor_exchange" and comm.size % 2 == 0:
            allgatherv_neighbor_exchange(comm, np.asarray(sendbuf), recvbuf,
                                         counts, displs)
        elif alg == "two_procs" and comm.size == 2:
            allgatherv_two_procs(comm, np.asarray(sendbuf), recvbuf, counts,
                                 displs)
        else:
            allgatherv_ring(comm, np.asarray(sendbuf), recvbuf, counts,
                            displs)
        return recvbuf

    def allgather(self, comm, sendbuf, recvbuf=None):
        sendbuf = np.asarray(sendbuf)
        if recvbuf is None:
            recvbuf = np.empty((comm.size,) + sendbuf.shape, sendbuf.dtype)
        if comm.size == 1:
            recvbuf.reshape(1, -1)[0] = sendbuf.reshape(-1)
            return recvbuf
        nbytes = sendbuf.nbytes
        pof2 = (comm.size & (comm.size - 1)) == 0
        even = comm.size % 2 == 0
        # sweep (TUNE_SWEEP.json winners: 64B bruck, 1K rd, 16K-256K
        # direct, 2M k_bruck): bruck tiny, recursive-doubling small-pof2,
        # direct messaging the mid band on small comms (one round, p-1
        # concurrent pairs), k-Bruck large on small comms (at p=4,radix=4
        # it is single-round direct with block coalescing). DEVIATION for
        # large comms: ring/neighbor-exchange despite never winning the
        # 4-rank sweep — p-1 concurrent pairs oversubscribe ports as p
        # grows, and the neighbor schedules are the topology-friendly
        # structural choice there (coll_base_allgather.c rationale)
        default = ("bruck" if nbytes <= 256
                   else ("recursive_doubling" if pof2 and nbytes <= (1 << 11)
                         else ("direct" if comm.size <= 8
                               and nbytes <= (1 << 18)
                               else ("k_bruck" if comm.size <= 8
                                     else ("bruck" if nbytes <= 4096
                                           # log p rounds, one msg/round —
                                           # no port pressure; keeps the
                                           # latency band off p-1 rings
                                           else ("neighbor_exchange" if even
                                                 else "ring"))))))
        alg = self._pick("allgather", comm, nbytes, default)
        if alg == "recursive_doubling" and pof2:
            allgather_recursive_doubling(comm, sendbuf, recvbuf)
        elif alg == "bruck":
            allgather_bruck(comm, sendbuf, recvbuf)
        elif alg == "sparbit":
            allgather_sparbit(comm, sendbuf, recvbuf)
        elif alg == "k_bruck":
            allgather_kbruck(
                comm, sendbuf, recvbuf,
                int(_var.get("coll_tuned_allgather_kbruck_radix", 4)))
        elif alg == "two_procs" and comm.size == 2:
            allgather_two_procs(comm, sendbuf, recvbuf)
        elif alg == "direct":
            allgather_direct(comm, sendbuf, recvbuf)
        elif alg == "linear":
            return self.basic.allgather(comm, sendbuf, recvbuf)
        elif alg == "neighbor_exchange" and even:
            allgather_neighbor_exchange(comm, sendbuf, recvbuf)
        else:
            allgather_ring(comm, sendbuf, recvbuf)
        return recvbuf

    def alltoall(self, comm, sendbuf, recvbuf=None):
        sendbuf = np.asarray(sendbuf)
        if recvbuf is None:
            recvbuf = np.empty_like(sendbuf)
        if comm.size == 1:
            recvbuf[...] = sendbuf
            return recvbuf
        nbytes = sendbuf.nbytes // comm.size   # per-destination bytes
        # sweep (TUNE_SWEEP.json, 4 ranks, winners keyed by TOTAL buffer;
        # per-dest = total/4): bruck wins the measured tiny point
        # (16 B/dest), plain linear the middle (256 B–4 KB/dest),
        # linear_sync the bandwidth regime (≥64 KB/dest — windowed flow
        # control beats the lockstep pairwise rounds). The bruck/linear
        # cutoff at 64 B/dest sits mid-gap between the two measured
        # points; pairwise stays selectable for large rank counts where
        # 2(p-1) outstanding requests oversubscribe
        default = ("bruck" if nbytes <= 64 else
                   ("linear" if nbytes <= (1 << 13) else "linear_sync"))
        alg = self._pick("alltoall", comm, nbytes, default)
        if alg == "bruck":
            alltoall_bruck(comm, sendbuf, recvbuf)
        elif alg == "linear_sync":
            alltoall_linear_sync(
                comm, sendbuf, recvbuf,
                int(_var.get("coll_tuned_alltoall_sync_requests", 8)))
        elif alg == "two_procs" and comm.size == 2:
            alltoall_two_procs(comm, sendbuf, recvbuf)
        elif alg == "linear":
            return self.basic.alltoall(comm, sendbuf, recvbuf)
        else:
            alltoall_pairwise(comm, sendbuf, recvbuf)
        return recvbuf

    def alltoallv(self, comm, sendbuf, recvbuf,
                  sendcounts, recvcounts, sdispls=None, rdispls=None):
        if comm.size == 1:
            return self.basic.alltoallv(comm, sendbuf, recvbuf, sendcounts,
                                        recvcounts, sdispls, rdispls)
        nbytes = int(np.sum(sendcounts)) * \
            np.asarray(sendbuf).dtype.itemsize
        alg = self._pick("alltoallv", comm, nbytes, "pairwise")
        if alg == "linear":
            return self.basic.alltoallv(comm, sendbuf, recvbuf, sendcounts,
                                        recvcounts, sdispls, rdispls)
        if sdispls is None:
            sdispls = list(np.concatenate([[0], np.cumsum(sendcounts)[:-1]]))
        if rdispls is None:
            rdispls = list(np.concatenate([[0], np.cumsum(recvcounts)[:-1]]))
        alltoallv_pairwise(comm, sendbuf, recvbuf, sendcounts, recvcounts,
                           sdispls, rdispls)
        return recvbuf

    def reduce_scatter(self, comm, sendbuf, recvbuf, counts, op: Op = None):
        op = _sum_default(op)
        sendbuf = np.asarray(sendbuf)
        if comm.size == 1 or not op.commutative:
            return self.basic.reduce_scatter(comm, sendbuf, recvbuf, counts,
                                             op)
        counts = [int(c) for c in counts]
        displs = list(np.concatenate([[0], np.cumsum(counts)[:-1]])
                      .astype(int))
        if recvbuf is None:
            recvbuf = np.empty(counts[comm.rank], sendbuf.dtype)
        pof2 = (comm.size & (comm.size - 1)) == 0
        nbytes = sendbuf.nbytes
        # sweep (TUNE_SWEEP.json, 4 ranks): recursive-halving wins small,
        # butterfly wins ≥16K at every size incl. 2M (fewer rounds than the
        # ring's p-1 for the same O(n) bytes); ring stays selectable for
        # topologies where only neighbor links are cheap
        default = ("recursive_halving" if (pof2 and nbytes <= (1 << 13))
                   else "butterfly")
        alg = self._pick("reduce_scatter", comm, nbytes, default)
        if alg == "nonoverlapping":
            return self.basic.reduce_scatter(comm, sendbuf, recvbuf, counts,
                                             op)
        if alg == "recursive_halving" and pof2:
            reduce_scatter_recursive_halving(comm, sendbuf, recvbuf, counts,
                                             displs, op)
        elif alg == "butterfly" or (alg == "recursive_halving" and not pof2):
            reduce_scatter_butterfly(comm, sendbuf, recvbuf, counts, displs,
                                     op)
        else:
            reduce_scatter_ring(comm, sendbuf, recvbuf, counts, displs, op)
        return recvbuf

    def reduce_scatter_block(self, comm, sendbuf, recvbuf=None, op: Op = None):
        op = _sum_default(op)
        sendbuf = np.asarray(sendbuf)
        if recvbuf is None:
            recvbuf = np.empty_like(sendbuf.reshape(comm.size, -1)[0])
        pof2 = (comm.size & (comm.size - 1)) == 0
        if comm.size == 1:
            recvbuf.reshape(-1)[:] = sendbuf.reshape(-1)
            return recvbuf
        if not op.commutative or sendbuf.size % comm.size != 0:
            return self.basic.reduce_scatter_block(comm, sendbuf, recvbuf, op)
        alg = self._pick("reduce_scatter_block", comm, sendbuf.nbytes,
                         "recursive_halving" if pof2 else "butterfly")
        if alg == "recursive_doubling" and pof2:
            reduce_scatter_block_recursive_doubling(comm, sendbuf, recvbuf,
                                                    op)
        elif alg == "butterfly" or not pof2:
            reduce_scatter_block_butterfly(comm, sendbuf, recvbuf, op)
        else:
            reduce_scatter_block_recursive_halving(comm, sendbuf, recvbuf, op)
        return recvbuf

    def barrier(self, comm):
        if comm.size <= 1:
            return
        alg = self._pick("barrier", comm, 0, "recursive_doubling")
        if alg == "double_ring":
            barrier_double_ring(comm)
        elif alg == "tree":
            barrier_tree(comm)
        elif alg == "two_procs" and comm.size == 2:
            barrier_two_procs(comm)
        else:
            # recursive_doubling; "bruck" (coll_base_barrier.c:269) is the
            # same +mask/-mask pairing here (see barrier_recursive_doubling)
            barrier_recursive_doubling(comm)

    def scan(self, comm, sendbuf, recvbuf=None, op: Op = None):
        op = _sum_default(op)
        send = _inplace(sendbuf, recvbuf)
        if recvbuf is None:
            recvbuf = np.empty_like(send)
        # sweep: rd wins only the latency regime; the linear chain moves
        # n bytes per rank once vs rd's n·log p (wins ≥1K on the sweep)
        if self._pick("scan", comm, send.nbytes,
                      "recursive_doubling" if send.nbytes < 1024
                      else "linear") == "linear":
            return self.basic.scan(comm, send, recvbuf, op)
        scan_recursive_doubling(comm, send, recvbuf, op, exclusive=False)
        return recvbuf

    def exscan(self, comm, sendbuf, recvbuf=None, op: Op = None):
        op = _sum_default(op)
        send = _inplace(sendbuf, recvbuf)
        if recvbuf is None:
            recvbuf = np.empty_like(send)
        if self._pick("exscan", comm, send.nbytes,
                      "recursive_doubling") == "linear":
            return self.basic.exscan(comm, send, recvbuf, op)
        scan_recursive_doubling(comm, send, recvbuf, op, exclusive=True)
        return recvbuf


@component("coll", "tuned", priority=30)
class TunedColl(Component):
    name = "tuned"

    def query(self, comm):
        return self.priority, TunedModule(comm)
