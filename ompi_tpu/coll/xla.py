"""coll/xla — ICI-native device collectives for the MPI-style comm API.

The component the whole design exists for (BASELINE.json north_star): when a
collective's buffers are device-resident (jax Arrays), dispatch to compiled
XLA collective programs over the communicator's mesh instead of staging
HBM→host like the reference's coll/accelerator shim
(ompi/mca/coll/accelerator/coll_accelerator_allreduce.c:31-60). Host (numpy)
buffers fall through to the host algorithms — the same buffer-type dispatch
the reference does with accelerator.check_addr (accelerator.h:171), with the
fast path inverted: device is native here, host is the staged case.

Selection: query() succeeds only for communicators with an attached device
mesh (``parallel.attach_mesh(comm, mesh, axis)``); priority 80 outranks
tuned(30)/basic(10), exactly how the north star requires coll/xla to win
MCA priority over coll/tuned for device buffers.
"""

from __future__ import annotations

import numpy as np

from ..core.component import Component, component
from ..op import SUM, Op
from .framework import CollModule
from .tuned import TunedModule


def _is_device(x) -> bool:
    from .. import accelerator

    return accelerator.check_addr(x) is not None


class XlaModule(CollModule):
    def __init__(self, comm) -> None:
        from ..parallel.collectives import DeviceComm

        self.dc: "DeviceComm" = comm.device_comm
        self.dc.spc = getattr(comm.ctx, "spc", None)
        self.host = TunedModule(comm)   # fallback for host buffers

    # Device layout contract: x is (n, *elem) sharded on dim 0 over the comm
    # axis — row i is "rank i"'s buffer (parallel/collectives.py docstring).

    def allreduce(self, comm, sendbuf, recvbuf=None, op: Op = None):
        op = op or SUM
        if not _is_device(sendbuf):
            return self.host.allreduce(comm, sendbuf, recvbuf, op)
        return self.dc.allreduce(sendbuf, op)

    def reduce(self, comm, sendbuf, recvbuf=None, op: Op = None, root: int = 0):
        op = op or SUM
        if not _is_device(sendbuf):
            return self.host.reduce(comm, sendbuf, recvbuf, op, root)
        return self.dc.reduce(sendbuf, op, root)

    def bcast(self, comm, buf, root: int = 0):
        if not _is_device(buf):
            return self.host.bcast(comm, buf, root)
        return self.dc.bcast(buf, root)

    def allgather(self, comm, sendbuf, recvbuf=None):
        if not _is_device(sendbuf):
            return self.host.allgather(comm, sendbuf, recvbuf)
        return self.dc.allgather(sendbuf)

    def alltoall(self, comm, sendbuf, recvbuf=None):
        if not _is_device(sendbuf):
            return self.host.alltoall(comm, sendbuf, recvbuf)
        return self.dc.alltoall(sendbuf)

    def reduce_scatter_block(self, comm, sendbuf, recvbuf=None, op: Op = None):
        op = op or SUM
        if not _is_device(sendbuf):
            return self.host.reduce_scatter_block(comm, sendbuf, recvbuf, op)
        return self.dc.reduce_scatter(sendbuf, op)

    def scan(self, comm, sendbuf, recvbuf=None, op: Op = None):
        op = op or SUM
        if not _is_device(sendbuf):
            return self.host.scan(comm, sendbuf, recvbuf, op)
        return self.dc.scan(sendbuf, op)

    def exscan(self, comm, sendbuf, recvbuf=None, op: Op = None):
        op = op or SUM
        if not _is_device(sendbuf):
            return self.host.exscan(comm, sendbuf, recvbuf, op)
        return self.dc.scan(sendbuf, op, exclusive=True)

    def barrier(self, comm):
        # host barrier still needed for rank processes; device barrier syncs
        # the mesh. Do both: host ranks agree, devices quiesce.
        self.host.barrier(comm)
        self.dc.barrier()

    # -- ragged / rooted entries: NATIVE ICI programs when the caller
    # presents the canonical padded device layout (DeviceComm docstring),
    # staged-host fallback otherwise. The reference implements these as
    # first-class host algorithms (coll_base_alltoallv.c:194 pairwise,
    # coll_base_allgatherv.c:95 bruck, coll_base_gather.c:41 binomial,
    # coll_base_scatter.c:63); the TPU-first shape is padded blocks + a
    # gather-map device argument (parallel/collectives.py ragged section),
    # so the EP/MoE alltoallv hot path never leaves ICI.

    def _to_host(self, x):
        from .. import accelerator

        info = accelerator.check_addr(x)
        if info is None:
            return x
        spc = self.dc.spc
        if spc is not None:
            spc.inc("device_stage_out_bytes", info.nbytes)
            spc.inc("coll_staged_fallbacks")
        return np.asarray(x)

    def _rows_ok(self, x, need_ndim: int) -> bool:
        """Canonical-layout gate: device buffer whose row dim covers the
        mesh axis (R % n == 0). Per-rank host-style buffers (the size>1
        process regime) miss the gate and stage — the same buffer-type
        dispatch check_addr does for host vs device."""
        if not _is_device(x) or x.ndim < need_ndim:
            return False
        R = x.shape[0]
        return R > 0 and R % self.dc.n == 0

    def allgatherv(self, comm, sendbuf, recvbuf=None, counts=None,
                   displs=None):
        if (counts is not None and displs is None and recvbuf is None
                and self._rows_ok(sendbuf, 2)
                and len(counts) == sendbuf.shape[0]
                and sendbuf.shape[1] >= max(int(c) for c in counts)):
            return self.dc.allgatherv(sendbuf, counts)
        return self.host.allgatherv(comm, self._to_host(sendbuf), recvbuf,
                                    counts, displs)

    def gather(self, comm, sendbuf, recvbuf=None, root: int = 0):
        if recvbuf is None and self._rows_ok(sendbuf, 2):
            return self.dc.gather(sendbuf, root)
        return self.host.gather(comm, self._to_host(sendbuf), recvbuf, root)

    def gatherv(self, comm, sendbuf, recvbuf=None, counts=None, displs=None,
                root: int = 0):
        if (counts is not None and displs is None and recvbuf is None
                and self._rows_ok(sendbuf, 2)
                and len(counts) == sendbuf.shape[0]
                and sendbuf.shape[1] >= max(int(c) for c in counts)):
            return self.dc.gatherv(sendbuf, counts, root)
        return self.host.basic.gatherv(comm, self._to_host(sendbuf), recvbuf,
                                       counts, displs, root)

    def scatter(self, comm, sendbuf, recvbuf=None, root: int = 0):
        if (recvbuf is None and self._rows_ok(sendbuf, 3)
                and sendbuf.shape[0] == sendbuf.shape[1]):
            return self.dc.scatter(sendbuf, root)
        return self.host.scatter(comm, self._to_host(sendbuf), recvbuf, root)

    def scatterv(self, comm, sendbuf, recvbuf, counts, displs=None,
                 root: int = 0):
        if (recvbuf is None and displs is None
                and self._rows_ok(sendbuf, 3)
                and sendbuf.shape[0] == sendbuf.shape[1]
                and len(counts) == sendbuf.shape[0]
                and sendbuf.shape[2] >= max(int(c) for c in counts)):
            return self.dc.scatterv(sendbuf, counts, root)
        return self.host.basic.scatterv(comm, self._to_host(sendbuf),
                                        recvbuf, counts, displs, root)

    def alltoallv(self, comm, sendbuf, recvbuf, sendcounts, recvcounts,
                  sdispls=None, rdispls=None):
        C = np.asarray(sendcounts)
        if (recvbuf is None and sdispls is None and rdispls is None
                and C.ndim == 2 and C.shape[0] == C.shape[1]
                and self._rows_ok(sendbuf, 3)
                and sendbuf.shape[0] == sendbuf.shape[1] == C.shape[0]
                and sendbuf.shape[2] >= int(C.max())):
            if recvcounts is not None:
                RC = np.asarray(recvcounts)
                # accept either the per-destination totals vector or the
                # stacked per-rank matrix (row j = what j receives from
                # each source, i.e. C.T)
                ok = (np.array_equal(RC, C.T) if RC.ndim == 2
                      else np.array_equal(RC.ravel(), C.sum(axis=0)))
                if not ok:
                    raise ValueError(
                        "alltoallv: recvcounts disagree with sendcounts "
                        f"({recvcounts} vs column sums "
                        f"{C.sum(axis=0).tolist()})")
            out, _tot = self.dc.alltoallv(sendbuf, C)
            return out
        return self.host.alltoallv(comm, self._to_host(sendbuf), recvbuf,
                                   sendcounts, recvcounts, sdispls, rdispls)

    def reduce_scatter(self, comm, sendbuf, recvbuf, counts, op: Op = None):
        op = op or SUM
        if (recvbuf is None and self._rows_ok(sendbuf, 2)
                and len(counts) == sendbuf.shape[0]
                and int(np.sum(counts)) == sendbuf.shape[1]):
            return self.dc.reduce_scatter_v(sendbuf, counts, op)
        return self.host.reduce_scatter(comm, self._to_host(sendbuf),
                                        recvbuf, counts, op)


@component("coll", "xla", priority=80)
class XlaColl(Component):
    name = "xla"

    def query(self, comm):
        if getattr(comm, "device_comm", None) is None:
            return None, None
        try:
            import jax  # noqa: F401
        except ImportError:  # pragma: no cover
            return None, None
        return self.priority, XlaModule(comm)
