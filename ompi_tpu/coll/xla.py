"""coll/xla — ICI-native device collectives for the MPI-style comm API.

The component the whole design exists for (BASELINE.json north_star): when a
collective's buffers are device-resident (jax Arrays), dispatch to compiled
XLA collective programs over the communicator's mesh instead of staging
HBM→host like the reference's coll/accelerator shim
(ompi/mca/coll/accelerator/coll_accelerator_allreduce.c:31-60). Host (numpy)
buffers fall through to the host algorithms — the same buffer-type dispatch
the reference does with accelerator.check_addr (accelerator.h:171), with the
fast path inverted: device is native here, host is the staged case.

Selection: query() succeeds only for communicators with an attached device
mesh (``parallel.attach_mesh(comm, mesh, axis)``); priority 80 outranks
tuned(30)/basic(10), exactly how the north star requires coll/xla to win
MCA priority over coll/tuned for device buffers.
"""

from __future__ import annotations

import numpy as np

from ..core.component import Component, component
from ..op import SUM, Op
from .framework import CollModule
from .tuned import TunedModule


def _is_device(x) -> bool:
    from .. import accelerator

    return accelerator.check_addr(x) is not None


class XlaModule(CollModule):
    def __init__(self, comm) -> None:
        from ..parallel.collectives import DeviceComm

        self.dc: "DeviceComm" = comm.device_comm
        self.dc.spc = getattr(comm.ctx, "spc", None)
        self.host = TunedModule(comm)   # fallback for host buffers

    # Device layout contract: x is (n, *elem) sharded on dim 0 over the comm
    # axis — row i is "rank i"'s buffer (parallel/collectives.py docstring).

    def allreduce(self, comm, sendbuf, recvbuf=None, op: Op = None):
        op = op or SUM
        if not _is_device(sendbuf):
            return self.host.allreduce(comm, sendbuf, recvbuf, op)
        return self.dc.allreduce(sendbuf, op)

    def reduce(self, comm, sendbuf, recvbuf=None, op: Op = None, root: int = 0):
        op = op or SUM
        if not _is_device(sendbuf):
            return self.host.reduce(comm, sendbuf, recvbuf, op, root)
        return self.dc.reduce(sendbuf, op, root)

    def bcast(self, comm, buf, root: int = 0):
        if not _is_device(buf):
            return self.host.bcast(comm, buf, root)
        return self.dc.bcast(buf, root)

    def allgather(self, comm, sendbuf, recvbuf=None):
        if not _is_device(sendbuf):
            return self.host.allgather(comm, sendbuf, recvbuf)
        return self.dc.allgather(sendbuf)

    def alltoall(self, comm, sendbuf, recvbuf=None):
        if not _is_device(sendbuf):
            return self.host.alltoall(comm, sendbuf, recvbuf)
        return self.dc.alltoall(sendbuf)

    def reduce_scatter_block(self, comm, sendbuf, recvbuf=None, op: Op = None):
        op = op or SUM
        if not _is_device(sendbuf):
            return self.host.reduce_scatter_block(comm, sendbuf, recvbuf, op)
        return self.dc.reduce_scatter(sendbuf, op)

    def scan(self, comm, sendbuf, recvbuf=None, op: Op = None):
        op = op or SUM
        if not _is_device(sendbuf):
            return self.host.scan(comm, sendbuf, recvbuf, op)
        return self.dc.scan(sendbuf, op)

    def exscan(self, comm, sendbuf, recvbuf=None, op: Op = None):
        op = op or SUM
        if not _is_device(sendbuf):
            return self.host.exscan(comm, sendbuf, recvbuf, op)
        return self.dc.scan(sendbuf, op, exclusive=True)

    def barrier(self, comm):
        # host barrier still needed for rank processes; device barrier syncs
        # the mesh. Do both: host ranks agree, devices quiesce.
        self.host.barrier(comm)
        self.dc.barrier()

    # -- long-tail entries without a native ICI program (v-variants,
    # rooted gathers/scatters): the coll/accelerator staging discipline
    # (coll_accelerator_allreduce.c:31-60) — stage device buffers to host
    # EXPLICITLY (SPC-accounted, never an implicit np.asarray deep in a
    # host algorithm), then run the host algorithm chain. Native ICI
    # versions can supersede these entry-by-entry later.

    def _to_host(self, x):
        from .. import accelerator

        info = accelerator.check_addr(x)
        if info is None:
            return x
        spc = self.dc.spc
        if spc is not None:
            spc.inc("device_stage_out_bytes", info.nbytes)
            spc.inc("coll_staged_fallbacks")
        return np.asarray(x)

    def allgatherv(self, comm, sendbuf, recvbuf=None, counts=None,
                   displs=None):
        return self.host.allgatherv(comm, self._to_host(sendbuf), recvbuf,
                                    counts, displs)

    def gather(self, comm, sendbuf, recvbuf=None, root: int = 0):
        return self.host.gather(comm, self._to_host(sendbuf), recvbuf, root)

    def gatherv(self, comm, sendbuf, recvbuf=None, counts=None, displs=None,
                root: int = 0):
        return self.host.basic.gatherv(comm, self._to_host(sendbuf), recvbuf,
                                       counts, displs, root)

    def scatter(self, comm, sendbuf, recvbuf=None, root: int = 0):
        return self.host.scatter(comm, self._to_host(sendbuf), recvbuf, root)

    def scatterv(self, comm, sendbuf, recvbuf, counts, displs=None,
                 root: int = 0):
        return self.host.basic.scatterv(comm, self._to_host(sendbuf),
                                        recvbuf, counts, displs, root)

    def alltoallv(self, comm, sendbuf, recvbuf, sendcounts, recvcounts,
                  sdispls=None, rdispls=None):
        return self.host.alltoallv(comm, self._to_host(sendbuf), recvbuf,
                                   sendcounts, recvcounts, sdispls, rdispls)

    def reduce_scatter(self, comm, sendbuf, recvbuf, counts, op: Op = None):
        return self.host.reduce_scatter(comm, self._to_host(sendbuf),
                                        recvbuf, counts, op)


@component("coll", "xla", priority=80)
class XlaColl(Component):
    name = "xla"

    def query(self, comm):
        if getattr(comm, "device_comm", None) is None:
            return None, None
        try:
            import jax  # noqa: F401
        except ImportError:  # pragma: no cover
            return None, None
        return self.priority, XlaModule(comm)
