"""coll/xla — ICI-native device collectives for the MPI-style comm API.

The component the whole design exists for (BASELINE.json north_star): when a
collective's buffers are device-resident (jax Arrays), dispatch to compiled
XLA collective programs over the communicator's mesh instead of staging
HBM→host like the reference's coll/accelerator shim
(ompi/mca/coll/accelerator/coll_accelerator_allreduce.c:31-60). Host (numpy)
buffers fall through to the host algorithms — the same buffer-type dispatch
the reference does with accelerator.check_addr (accelerator.h:171), with the
fast path inverted: device is native here, host is the staged case.

Selection: query() succeeds only for communicators with an attached device
mesh (``parallel.attach_mesh(comm, mesh, axis)``); priority 80 outranks
tuned(30)/basic(10), exactly how the north star requires coll/xla to win
MCA priority over coll/tuned for device buffers.
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

from ..core import var as _var
from ..core.component import Component, component
from ..op import SUM, Op
from .framework import CollModule
from .tuned import TunedModule


def _is_device(x) -> bool:
    from .. import accelerator

    return accelerator.check_addr(x) is not None


# -- device decision layer (≙ coll_tuned_decision_fixed.c:55-104 +
#    coll_tuned_dynamic_file.c:58, applied to the DEVICE path) --------------
#
# The host components pick an algorithm per (comm size, msg size); the
# device component picks a MODE per (collective, device count, msg size):
# "native" runs the ICI program, "staged" takes the explicit D2H → host op
# → H2D round trip (the coll/accelerator shim as a *measured choice*, not
# a fallback). Fixed defaults come from the recorded sweep
# (BENCH_SWEEP_cpu_8dev.json): on the CPU test fabric the shard_map
# dispatch overhead loses to one memcpy for dense alltoall below ~32 MB
# (0.8-0.99x), while every other entry wins native at every size; on real
# accelerator platforms staging crosses the host bridge so native always
# wins — the platform gates the default.

_var.register("coll", "xla", "mode", "", type=str, level=3,
              help="Force device-collective mode for every entry: "
                   "native|staged|quant|hier|hier+quant (empty = "
                   "per-entry decision; quant/hier apply to entries "
                   "with that arm, others keep the auto decision).")
_var.register("coll", "xla", "dynamic_rules", "", type=str, level=4,
              help="Path to a device decision rules file: lines of "
                   "'<coll>[@<plane>] <min_ndev> <min_bytes> "
                   "<native|staged|quant|bidir|hier|hier+quant>' "
                   "(plane in {ici,dcn}; plane-keyed rows beat plain "
                   "rows on comms spanning that plane).")
_var.register("coll", "xla", "grad_bucket_bytes", 4 << 20, type=int, level=3,
              help="Target bytes per gradient-sync bucket for the "
                   "bucketed overlap tier (parallel/overlap): grads are "
                   "flattened into fixed-byte buckets in reverse-layer "
                   "order and each bucket allreduces as soon as its "
                   "leaves are produced in the backward pass.")
# the blanket quantization switch (env OMPI_TPU_COLL_QUANT):
#   on/force -> quantize every eligible reduction at any size
#   off      -> never pick quant, even when a rules file says so
#   (empty)  -> rules decide, subject to the min_bytes floor below
_var.register("COLL_QUANT", "", "", "", type=str, level=2,
              help="Blanket switch for the block-quantized device tier: "
                   "on/force | off | empty (rules decide).")
_var.register("coll", "quant", "min_bytes", 1 << 20, type=int, level=3,
              help="Per-rank byte floor below which rule-selected quant "
                   "keeps the exact arm (small messages are latency-, "
                   "not wire-bound; quantization error buys nothing).")

_DECIDED = ("allreduce", "reduce", "bcast", "allgather", "alltoall",
            "reduce_scatter_block", "scan", "exscan", "allgatherv",
            "gather", "gatherv", "scatter", "scatterv", "alltoallv",
            "reduce_scatter")
# entries with a quantized arm (coll/quant engine entry points; grad_sync
# buckets ride psum_quant so they carry one too, and the serving tier's
# decode combines ride the same allgather/reduce_scatter quant engines)
_QUANT_COLLS = ("allreduce", "reduce_scatter_block", "reduce_scatter",
                "allgather", "grad_sync", "decode_ag", "decode_rs")
for _c in _DECIDED:
    _var.register("coll", "xla", f"{_c}_mode", "", type=str, level=3,
                  help=f"Force the {_c} device mode (native|staged"
                       + ("|quant" if _c in _QUANT_COLLS else "")
                       + "; empty = auto).")
# overlap-tier decision points (not XlaModule entries): the bucketed
# gradient sync (parallel/overlap) and the collective-matmul ring
# direction (ops/collective_matmul via Config(tp_overlap="fused"))
_var.register("coll", "xla", "grad_sync_mode", "", type=str, level=3,
              help="Force the gradient-sync bucket arm (native|quant|"
                   "hier|hier+quant; empty = auto via DEVICE_RULES "
                   "grad_sync rows).")
_var.register("coll", "xla", "collmm_mode", "", type=str, level=3,
              help="Force the collective-matmul ring schedule "
                   "(native = unidirectional ring | bidir = two "
                   "half-rings on both ICI directions; empty = auto "
                   "via DEVICE_RULES collmm rows).")
_var.register("coll", "xla", "reshard_mode", "", type=str, level=3,
              help="Force the reshard plan-step arm (native; empty = "
                   "auto via DEVICE_RULES reshard rows / the learned "
                   "ledger). Plan steps are layout-pure single "
                   "collectives, so native is the only executable arm "
                   "today; the var exists so the decision chain stays "
                   "uniform and future staged/quant step arms slot in.")
_var.register("coll", "xla", "moe_dispatch_mode", "", type=str, level=3,
              help="Force the MoE token-dispatch exchange arm (native|"
                   "hier|hier+quant; empty = auto via DEVICE_RULES "
                   "moe_dispatch rows). hier splits the ragged exchange "
                   "into same-outer-group and cross-DCN lanes; dispatch "
                   "payloads are never quantized (hier+quant decays to "
                   "hier here — quant applies to the combine only).")
_var.register("coll", "xla", "decode_ag_mode", "", type=str, level=3,
              help="Force the serving decode allgather arm (native|"
                   "quant; empty = auto via DEVICE_RULES decode_ag "
                   "rows). Carries every decode-path feature combine "
                   "(embed, attention heads, o/mlp projections) plus "
                   "the logits-psum gather half; quant rides the "
                   "EQuARX int8 block tier.")
_var.register("coll", "xla", "decode_rs_mode", "", type=str, level=3,
              help="Force the serving decode reduce-scatter arm "
                   "(native|quant; empty = auto via DEVICE_RULES "
                   "decode_rs rows). Carries the logits-psum reduce "
                   "half — the B×vocab float32 payload that dominates "
                   "decode wire bytes.")
_var.register("coll", "xla", "moe_combine_mode", "", type=str, level=3,
              help="Force the MoE expert-output combine exchange arm "
                   "(native|hier|hier+quant; empty = auto via "
                   "DEVICE_RULES moe_combine rows). hier+quant sends "
                   "the cross-DCN lane on the EQuARX int8 block tier; "
                   "the same-outer-group lane stays full precision.")
_var.register("coll", "xla", "rules", "", type=str, level=3,
              help="Arm-selection source: empty/'static' = platform "
                   "default + DEVICE_RULES rows; 'learned' = consult "
                   "the perf cost-model ledger first (best modeled "
                   "busbw at the observed size, reason "
                   "'learned:<a>=..GBps-vs-<b>=..GBps'), falling "
                   "through to the static chain on a model miss. "
                   "Force vars and blanket switches still outrank.")

# every mode any decision point can name (rules-file vocabulary);
# "hier" = the two-tier HAN arm (reduce_scatter ICI -> allreduce DCN on
# the scattered 1/n_inner -> allgather ICI), "hier+quant" the same shape
# with ONLY the outer (DCN) stage on the EQuARX quantized tier.
# The authoritative copies live in analysis/rules.py (the grammar
# module CI shares); the asserts keep the two import paths in lockstep.
from ..analysis import rules as _rules_grammar

_MODES = _rules_grammar.MODES
_PLANES = _rules_grammar.PLANES
assert _MODES == ("native", "staged", "quant", "bidir", "hier",
                  "hier+quant")
assert _PLANES == ("ici", "dcn")


def _load_device_rules(path: Optional[str] = None):
    """Parse a device decision rules file into (coll, min_ndev,
    min_bytes, mode) rows.  With no argument the configured
    ``coll_xla_dynamic_rules`` path is read (the dispatch-time caller);
    an explicit path serves offline consumers — the trace analyzer's
    decision-drift check re-evaluates audited arms against any rules
    file, e.g. the repo's DEVICE_RULES.txt.

    The coll column may be plane-keyed: ``<coll>@<plane>`` (plane in
    {ici, dcn}) rows apply only to communicators whose axes include
    that plane and BEAT plain rows for the same coll at decision time
    (decide_mode's two-lane rule walk).  An unknown plane is a loud
    ValueError — a typo must not silently deactivate a row.  Parsing
    is delegated to ``analysis.rules`` (the grammar module CI shares),
    which also rejects an exactly-duplicated
    ``(coll[@plane], min_ndev, min_bytes)`` key naming both lines —
    before that validator the later row silently won the rule walk."""
    if path is None:
        path = _var.get("coll_xla_dynamic_rules", "")
    if not path:
        return []
    return _rules_grammar.parse_file(path)


def _quant_pads_past_native(coll: str, nbytes: int, ndev: int,
                            dtype) -> bool:
    """True when the quantized arm's BLOCK PADDING pushes its wire
    bytes past the native arm's for this payload: the per-rank shard
    pads up to ``coll_quant_block`` elements before the int8 cast, so a
    small-payload/large-block combination (the decode footgun:
    KB-scale decode_ag shards under ``coll_quant_block=32``… or worse,
    the 256 default) can make "compression" a strict loss.  The
    decision layer records ``ineligible:quant:pad-past-native`` instead
    of silently shipping more bytes than native would."""
    if dtype is None:
        return False
    from .quant import wire_bytes
    try:
        count = max(int(nbytes) // np.dtype(dtype).itemsize, 1)
        qcoll = ("allreduce" if coll == "allreduce" else
                 "reduce_scatter" if ("reduce_scatter" in coll
                                      or coll.endswith("_rs"))
                 else "allgather")
        wb = wire_bytes(qcoll, count, max(int(ndev), 1), dtype)
    except (ValueError, TypeError, KeyError):
        return False     # no quant wire model for this coll/dtype
    return wb["quant_bytes"] > wb["native_bytes"]


def decide_mode(coll: str, nbytes: int, ndev: int, platform: str,
                rules, allowed, quant_ok: bool = False,
                dtype=None, op: Op = None, plane: Optional[str] = None,
                hier_ok: bool = False, hier_why: str = "") -> tuple:
    """The device decision-precedence chain as a reusable module-level
    function, returned as (arm, reason, chain): per-entry force var >
    blanket coll_xla_mode > blanket COLL_QUANT > platform default, then
    DEVICE_RULES rows (later lines win; quant rows vetoed by the off
    switch, the coll_quant_min_bytes floor, or op/dtype/layout
    ineligibility).  ``reason`` is the link that decided; ``chain``
    records every vetoed/skipped link so trace.explain_last can show the
    full evaluation.

    ``allowed`` is the set of arms the calling entry can actually execute
    for this buffer/op — the decision never names an arm the entry would
    silently ignore.  XlaModule dispatches funnel through here (via
    ``_decide``); the overlap tier calls it directly with the coll names
    ``grad_sync`` (bucketed dp gradient sync, native|quant|hier) and
    ``collmm`` (collective-matmul ring direction, native|bidir).

    Two-tier extensions: ``plane`` is the calling comm's plane context
    ('dcn' when any comm axis crosses a DCN boundary, else 'ici') —
    ``<coll>@<plane>`` rule rows match only their plane and BEAT plain
    rows for the same coll (their vetoes included).  The hierarchical
    arms (hier, hier+quant) are gated by ``hier_ok`` instead of
    ``allowed``: an ineligible comm (flat mesh, single axis, non-sum
    op) records the audited ``ineligible:hier:<hier_why>`` veto, and an
    explicit per-entry force of an impossible hier raises."""
    from .quant import check_quantizable

    chain: list = []
    qvar = str(_var.get("COLL_QUANT", "") or "").strip().lower()
    ent = _var.get(f"coll_xla_{coll}_mode", "")
    forced = ent or _var.get("coll_xla_mode", "")
    src = f"coll_xla_{coll}_mode" if ent else "coll_xla_mode"
    if forced:
        if forced not in _MODES:
            raise ValueError(
                f"coll_xla mode for {coll!r} is {forced!r} "
                f"(want one of {', '.join(_MODES)})")
        if forced == "quant":
            if coll in _QUANT_COLLS:
                if "quant" in allowed:
                    # invalid op/dtype under an explicit quant force
                    # must fail loudly, not silently take the exact
                    # path
                    check_quantizable(op or SUM,
                                      dtype if dtype is not None
                                      else np.float32)
                    return "quant", f"force:{src}=quant", chain
                chain.append(f"force:{src}=quant skipped "
                             "(layout has no quantized arm)")
            elif ent:
                raise ValueError(
                    f"collective {coll!r} has no quantized arm "
                    f"(quant applies to {', '.join(_QUANT_COLLS)})")
            else:
                chain.append("force:coll_xla_mode=quant skipped "
                             "(entry has no quantized arm)")
            # global quant force: entries without a quantized arm
            # keep the auto decision below
        elif forced in ("hier", "hier+quant"):
            if not hier_ok:
                if ent:
                    # a per-entry force of an impossible hier must fail
                    # loudly, not silently take the flat path
                    raise ValueError(
                        f"coll_xla mode for {coll!r} forces {forced} "
                        f"but the comm is ineligible: {hier_why}")
                chain.append(f"force:{src}={forced} skipped "
                             f"(ineligible:hier:{hier_why})")
            elif forced == "hier+quant" and not quant_ok:
                if ent:
                    check_quantizable(op or SUM,
                                      dtype if dtype is not None
                                      else np.float32)
                chain.append(f"force:{src}={forced} skipped "
                             "(op/dtype has no quantized outer stage)")
            else:
                return forced, f"force:{src}={forced}", chain
        elif forced in allowed:
            return forced, f"force:{src}={forced}", chain
        else:
            chain.append(f"force:{src}={forced} skipped "
                         f"(no {forced} kernel for this op/layout)")
    q_ok = quant_ok and "quant" in allowed
    if qvar in ("1", "on", "true", "yes", "force"):
        if q_ok:
            return "quant", f"blanket:COLL_QUANT={qvar}", chain
        if coll in _QUANT_COLLS:
            chain.append(f"blanket:COLL_QUANT={qvar} skipped "
                         "(op/dtype/layout ineligible)")
    quant_off = qvar in ("0", "off", "false", "no")
    floor = int(_var.get("coll_quant_min_bytes", 1 << 20))
    source = str(_var.get("coll_xla_rules", "") or "").strip().lower()
    if source == "learned":
        # cost-model source (ompi_tpu/perf): best modeled busbw at this
        # size wins.  Quant stays subject to the same eligibility gates
        # as a quant rules row; a model miss falls through to the static
        # chain below so a cold ledger never strands a collective.
        from .. import perf
        cand = tuple(m for m in allowed
                     if m != "quant"
                     or (q_ok and not quant_off and nbytes >= floor
                         and not _quant_pads_past_native(
                             coll, nbytes, ndev, dtype)))
        if hier_ok:
            cand = cand + ("hier",)
            if quant_ok and not quant_off:
                cand = cand + ("hier+quant",)
        learned = perf.best_arm(coll, nbytes, cand)
        if learned is not None:
            return learned[0], learned[1], chain
        chain.append(f"learned: no modeled data for {coll}@{nbytes}B "
                     "(falling through to static chain)")
    elif source and source != "static":
        raise ValueError(f"coll_xla_rules is {source!r} "
                         "(want 'learned', 'static' or empty)")
    if platform == "cpu":
        # sweep-derived (BENCH_SWEEP_cpu_8dev.json): dense alltoall
        # staged wins 1KB-16MB/rank on the CPU fabric; all else native
        pick = "staged" if (coll == "alltoall"
                            and nbytes < (32 << 20)) else "native"
    else:
        pick = "native"       # staging crosses the host bridge
    if pick not in allowed:
        pick = "native"
    reason = f"default:platform={platform}"

    def _veto_of(mode: str, rule: str) -> Optional[str]:
        """Gates shared by plain and plane-keyed rows.  The quant floor
        deliberately does NOT veto hier+quant: only the scattered
        1/n_inner fraction is quantized there, so the flat-arm latency
        calculus behind the floor does not carry over."""
        if mode in ("quant", "hier+quant"):
            if quant_off:
                return f"off:COLL_QUANT={qvar} (vetoed {rule})"
            if not (q_ok if mode == "quant" else quant_ok):
                return f"ineligible:op/dtype/layout (vetoed {rule})"
            if mode == "quant" and nbytes < floor:
                return (f"floor:coll_quant_min_bytes={floor}"
                        f">{nbytes} (vetoed {rule})")
            if mode == "quant" and _quant_pads_past_native(
                    coll, nbytes, ndev, dtype):
                return (f"ineligible:quant:pad-past-native "
                        f"(block padding exceeds native bytes at "
                        f"{nbytes}B; vetoed {rule})")
        if mode in ("hier", "hier+quant") and not hier_ok:
            return f"ineligible:hier:{hier_why} (vetoed {rule})"
        return None

    # two-lane walk: plain rows accumulate as before; '<coll>@<plane>'
    # rows matching the comm's plane accumulate separately and override
    # the plain lane at the end (vetoes included — a vetoed plane row's
    # reason still beats a plain row's pick)
    p_pick: Optional[str] = None
    p_reason: Optional[str] = None
    for c, mn, mb, mode in rules:
        base_coll, _, row_plane = c.partition("@")
        if base_coll != coll or ndev < mn or nbytes < mb:
            continue
        if row_plane and row_plane != (plane or ""):
            continue
        rule = f"rule:{c} {mn} {mb} {mode}"
        veto = _veto_of(mode, rule)
        if veto is not None:
            # vetoed rule: keep the prior pick, but the veto IS the
            # deciding word unless a later rule overrides it
            chain.append(veto)
            if row_plane:
                p_reason = veto
            else:
                reason = veto
            continue
        if mode not in ("hier", "hier+quant") and mode not in allowed:
            chain.append(f"{rule} skipped (no {mode} kernel)")
            continue
        if row_plane:
            p_pick, p_reason = mode, rule
        else:
            pick, reason = mode, rule
        chain.append(rule)
    if p_reason is not None:
        reason = p_reason
    if p_pick is not None:
        pick = p_pick
    return pick, reason, chain


# numpy reduction kernels for the staged arm (standard MPI ops only; a
# custom op keeps the native path regardless of decision — its fn is
# jax-traceable, not a host kernel)
_NP_FOLD = {"sum": np.add.reduce, "max": np.maximum.reduce,
            "min": np.minimum.reduce, "prod": np.multiply.reduce}


def _staged_allgather(h: np.ndarray) -> np.ndarray:
    """Host allgather on the canonical layout (staged arm of both
    allgather and gather — MPI promises only the root's row for gather)."""
    flat = h.reshape((-1,) + h.shape[2:]) if h.ndim > 2 else h.reshape(-1)
    return np.broadcast_to(flat[None], (h.shape[0],) + flat.shape)


def _staged_allgatherv(h: np.ndarray, counts) -> np.ndarray:
    """Host allgatherv on the padded canonical layout (also the gatherv
    staged arm)."""
    cat = np.concatenate([h[i, :int(c)] for i, c in enumerate(counts)])
    return np.broadcast_to(cat[None], (h.shape[0],) + cat.shape)


class XlaModule(CollModule):
    def __init__(self, comm) -> None:
        from ..parallel.collectives import DeviceComm

        self.dc: "DeviceComm" = comm.device_comm
        self.dc.spc = getattr(comm.ctx, "spc", None)
        self.host = TunedModule(comm)   # fallback for host buffers
        self._comm = comm               # decision-audit wire accounting
        self._rules = _load_device_rules()
        self._platform = next(iter(self.dc.mesh.devices.flat)).platform
        # two-tier context, fixed at attach time: whether the comm's
        # axis (tuple) spans an inner ICI + outer DCN split (the hier
        # arm's eligibility) and which plane keys '<coll>@<plane>' rows
        from ..parallel.hierarchy import classify_axes, hier_axes
        self._hier_inner, self._hier_outer, self._hier_why = hier_axes(
            self.dc.mesh, self.dc.axis)
        axes = (self.dc.axis if isinstance(self.dc.axis, tuple)
                else (self.dc.axis,))
        kinds = classify_axes(self.dc.mesh)
        self._plane = ("dcn" if any(kinds.get(a) == "dcn" for a in axes)
                       else "ici")

    # Device layout contract: x is (n, *elem) sharded on dim 0 over the comm
    # axis — row i is "rank i"'s buffer (parallel/collectives.py docstring).

    # -- decision (native ICI program vs measured host staging) -------------

    _ALL_ARMS = ("native", "staged", "quant")

    def _mode(self, coll: str, x, op: Op = None,
              allowed=_ALL_ARMS, weights=None, extra=None) -> str:
        """Pick per (collective, PER-RANK bytes, dtype) — the unit the
        sweep measures and the rules file records (a canonical array's
        row 0 is one rank's buffer), so thresholds line up with the
        evidence. Three arms: native ICI program, measured host staging,
        and the block-quantized tier (coll/quant) for float reductions.

        ``allowed`` is the set of arms the CALLING entry can actually
        execute for this buffer/op (a non-foldable op has no host staging
        kernel; a 1-D allgather has no quantized layout) — the decision
        never names an arm the entry would silently ignore, so the audit
        event always matches the executed path.  Every device dispatch
        funnels through here exactly once: one decision-audit record per
        collective."""
        pick, reason, chain = self._decide(coll, x, op, allowed)
        self._audit(coll, x, op, pick, reason, chain, weights=weights,
                    extra=extra)
        return pick

    def _decide(self, coll: str, x, op: Op, allowed) -> tuple:
        """Module-entry shim over :func:`decide_mode`: per-RANK bytes from
        the canonical layout, quant eligibility from the op/dtype gate,
        hier eligibility from the comm's two-tier context."""
        nbytes = x.nbytes // max(x.shape[0], 1)
        hier_ok, hier_why = self._hier_eligible(coll, op)
        return decide_mode(coll, nbytes, self.dc.n, self._platform,
                           self._rules, allowed,
                           quant_ok=self._quant_ok(coll, x, op),
                           dtype=x.dtype, op=op, plane=self._plane,
                           hier_ok=hier_ok, hier_why=hier_why)

    def _hier_eligible(self, coll: str, op: Op = None) -> tuple:
        """(ok, why-not) for the hierarchical arm on this entry: only
        allreduce has a hier kernel, the comm must span a real two-tier
        axis split (hier_axes), and the staged shape reduces via psum —
        sum only."""
        if coll != "allreduce":
            return False, "entry has no hierarchical kernel"
        if self._hier_inner is None:
            return False, self._hier_why
        if (op or SUM).name != "sum":
            return False, (f"op {(op or SUM).name} has no hierarchical "
                           "reduce (psum stages are sum-only)")
        return True, ""

    # modeled wire-byte collectives: coll -> coll/quant hop-table name
    _WIRE_MODEL = {"allreduce": "allreduce",
                   "reduce_scatter_block": "reduce_scatter",
                   "reduce_scatter": "reduce_scatter",
                   "allgather": "allgather"}

    def _audit(self, coll: str, x, op: Op, arm: str, reason: str,
               chain: list, weights=None, extra=None) -> None:
        """ONE decision-audit record per device-dispatched collective.
        Always: the arm-count + wire-byte pvars (plain dict adds, same
        cost class as every other SPC site) and the monitoring wire-byte
        correction when the quant arm will carry the call (the logical
        f32 size the dispatch layer recorded is not what travels).
        When tracing is on: the full decision event with the precedence
        chain, feeding trace.explain_last."""
        from .. import trace

        rows = max(x.shape[0], 1)
        nbytes = x.nbytes // rows
        wire = nbytes
        ratio = None
        hier_split = None
        if arm in ("hier", "hier+quant"):
            # the HAN stage math is the wire model: inner RS + AG at
            # (ni-1)/ni each, outer allreduce on the scattered 1/ni
            # fraction (quantized for hier+quant — the inner stages
            # stay native, so only the outer figure shrinks)
            from ..parallel.hierarchy import hier_wire_bytes
            ni = self.dc.mesh.shape[self._hier_inner]
            no = self.dc.mesh.shape[self._hier_outer]
            hw = hier_wire_bytes(max(x.size // rows, 1), x.dtype, ni, no,
                                 quant=(arm == "hier+quant"))
            wire = hw["total_bytes"]
            ratio = hw["ratio"]
            hier_split = (self._hier_inner, self._hier_outer,
                          hw["inner_stage_bytes"], hw["outer_bytes"],
                          hw["outer_native_bytes"])
            if arm == "hier+quant":
                from .. import monitoring
                monitoring.coll_wire_event(self._comm, coll, wire,
                                           x.nbytes)
        else:
            qcoll = self._WIRE_MODEL.get(coll)
            if qcoll is not None:
                from .quant import wire_bytes
                try:
                    wb = wire_bytes(qcoll, max(x.size // rows, 1),
                                    self.dc.n, x.dtype)
                except (ValueError, TypeError):
                    wb = None
                if wb is not None:
                    ratio = wb["ratio"]
                    if arm == "quant":
                        wire = wb["quant_bytes"]
                    elif arm == "native":
                        wire = wb["native_bytes"]
                    if arm == "quant":
                        from .. import monitoring
                        # satellite fix: record_coll logged the logical
                        # size; correct the coll matrix to
                        # int8-payload+scales
                        monitoring.coll_wire_event(
                            self._comm, coll, wb["quant_bytes"], x.nbytes)
        spc = self.dc.spc
        if spc is not None:
            spc.inc(f"coll_arm_{arm}_count")
            spc.inc("coll_wire_bytes", wire)
        from ..parallel import simdcn
        if simdcn.us_per_mib() > 0:
            # simulated-DCN delay shim: charge the bytes this arm's
            # geometry moves across the simulated slow plane (hier pays
            # only its outer stage — the skew the hier arm exists for)
            if hier_split is not None:
                simdcn.charge(hier_split[3])
            elif arm != "staged":
                simdcn.charge(int(wire * simdcn.ring_dcn_fraction(
                    self.dc.mesh, self.dc.axis)))
        from .. import health, numerics, perf
        if health.enabled:
            # fold the decided arm into the in-flight entry's signature —
            # the last field of the flight-recorder hash (op, dtype,
            # count, reduction, arm)
            health.note_arm(arm)
        if numerics.enabled:
            # annotate the in-flight fingerprint entry so the non-finite
            # verdict names the executed arm (compare semantics differ:
            # bitwise on native, tolerance-bounded on quant)
            numerics.note_arm(arm)
        if perf.enabled:
            # annotate the in-flight timing entry (coll/framework's
            # dispatch wrapper) with the executed arm + audited per-rank
            # wire bytes; only annotated samples fold into the model
            perf.note_arm(arm, nbytes=wire, ndev=self.dc.n)
        from .. import traffic
        if traffic.enabled:
            # per-edge attribution of the SAME wire figure the pvar just
            # banked — the conservation invariant's other half (hier
            # passes its stage split so the matrix charges inner RS/AG
            # rings + the outer ring instead of one flat ring)
            traffic.note_coll(self.dc, coll, arm, wire, weights=weights,
                              hier=hier_split)
        if trace.enabled:
            bucket = 1 << max(int(nbytes) - 1, 0).bit_length()
            ctx = getattr(self._comm, "ctx", None)
            extra = dict(extra or {})
            if hier_split is not None:
                extra.update({"hier_inner": hier_split[0],
                              "hier_outer": hier_split[1],
                              "hier_inner_bytes": 2 * hier_split[2],
                              "hier_outer_bytes": hier_split[3]})
            trace.decision(
                coll, arm=arm, reason=reason, verdict=None,
                nbytes=nbytes, rank=getattr(ctx, "rank", 0),
                shape_bucket=bucket, shape=tuple(x.shape),
                dtype=str(x.dtype),
                reduce_op=getattr(op, "name", None),
                ndev=self.dc.n, wire_bytes=wire, quant_ratio=ratio,
                chain=list(chain), **extra)

    def _quant_ok(self, coll: str, x, op: Op = None) -> bool:
        """Whether the quantized arm can carry this call at all
        (decision-level gate; the engine re-checks and raises)."""
        from ..op import quantizable

        return coll in _QUANT_COLLS and quantizable(op or SUM, x.dtype)

    def _stage_out(self, x) -> np.ndarray:
        """The explicit D2H half of the staged arm (SPC-accounted);
        accepts a raw jax array or a DeviceBuffer holder."""
        import jax

        from .. import accelerator

        if isinstance(x, accelerator.DeviceBuffer):
            x = x.array
        spc = self.dc.spc
        h = np.asarray(jax.device_get(x))
        if spc is not None:
            spc.inc("device_stage_out_bytes", h.nbytes)
            spc.inc("coll_staged_fallbacks")
        return h

    def _stage_in(self, h: np.ndarray):
        """H2D back onto the canonical sharding."""
        import jax
        import jax.numpy as jnp

        spc = self.dc.spc
        if spc is not None:
            spc.inc("device_stage_in_bytes", h.nbytes)
        return jax.device_put(jnp.asarray(h), self.dc.sharding())

    def allreduce(self, comm, sendbuf, recvbuf=None, op: Op = None):
        op = op or SUM
        if not _is_device(sendbuf):
            return self.host.allreduce(comm, sendbuf, recvbuf, op)
        mode = self._mode("allreduce", sendbuf, op,
                          allowed=self._ALL_ARMS
                          if op.name in _NP_FOLD
                          else ("native", "quant"))
        if mode in ("hier", "hier+quant"):
            return self._hier_allreduce(sendbuf, op,
                                        quant=(mode == "hier+quant"))
        if mode == "quant":
            return self.dc.quant.allreduce(sendbuf, op)
        if mode == "staged":
            h = self._stage_out(sendbuf)
            red = _NP_FOLD[op.name](h, axis=0)
            return self._stage_in(np.broadcast_to(red, h.shape))
        return self.dc.allreduce(sendbuf, op)

    def _hier_allreduce(self, x, op: Op, quant: bool):
        """The two-tier HAN arm: reduce_scatter(inner ICI) →
        allreduce(outer DCN, on the scattered 1/n_inner — quantized
        when ``quant``) → allgather(inner ICI), compiled through the
        same executable cache as every flat arm.  Only reachable when
        the decision layer said so, i.e. the comm spans a two-tier axis
        split and op is sum."""
        import jax.numpy as jnp

        from ..parallel.hierarchy import (hierarchical_psum,
                                          hierarchical_psum_quant)
        dc = self.dc
        inner, outer = self._hier_inner, self._hier_outer
        no = dc.mesh.shape[outer]
        key = ("hier_allreduce", bool(quant), inner, outer, x.shape,
               str(x.dtype))

        def build():
            def fn(xs):              # (r, *e) local rows
                red = dc._fold_local(xs, op)
                shape = red.shape
                flat = red.reshape(-1)
                if quant:
                    out = hierarchical_psum_quant(flat, inner, outer, no)
                else:
                    out = hierarchical_psum(flat, inner, outer)
                return jnp.broadcast_to(out.reshape(shape)[None],
                                        xs.shape)
            return dc._shard_map(fn, dc._spec, dc._spec)

        return dc._compiled(key, build)(x)

    def reduce(self, comm, sendbuf, recvbuf=None, op: Op = None, root: int = 0):
        op = op or SUM
        if not _is_device(sendbuf):
            return self.host.reduce(comm, sendbuf, recvbuf, op, root)
        mode = self._mode("reduce", sendbuf, op,
                          allowed=("native", "staged")
                          if op.name in _NP_FOLD else ("native",))
        if mode == "staged":
            h = self._stage_out(sendbuf)
            red = _NP_FOLD[op.name](h, axis=0)
            return self._stage_in(np.broadcast_to(red, h.shape))
        return self.dc.reduce(sendbuf, op, root)

    def bcast(self, comm, buf, root: int = 0):
        if not _is_device(buf):
            return self.host.bcast(comm, buf, root)
        if self._mode("bcast", buf) == "staged":
            h = self._stage_out(buf)
            return self._stage_in(np.broadcast_to(h[root], h.shape))
        return self.dc.bcast(buf, root)

    def allgather(self, comm, sendbuf, recvbuf=None):
        if not _is_device(sendbuf):
            return self.host.allgather(comm, sendbuf, recvbuf)
        mode = self._mode("allgather", sendbuf,
                          allowed=self._ALL_ARMS if sendbuf.ndim >= 2
                          else ("native", "staged"))
        if mode == "quant":
            return self.dc.quant.allgather(sendbuf)
        if mode == "staged":
            return self._stage_in(_staged_allgather(self._stage_out(sendbuf)))
        return self.dc.allgather(sendbuf)

    def alltoall(self, comm, sendbuf, recvbuf=None):
        if not _is_device(sendbuf):
            return self.host.alltoall(comm, sendbuf, recvbuf)
        if self._mode("alltoall", sendbuf) == "staged":
            h = self._stage_out(sendbuf)           # (R, R, b, *e)
            return self._stage_in(np.ascontiguousarray(
                np.swapaxes(h, 0, 1)))
        return self.dc.alltoall(sendbuf)

    def reduce_scatter_block(self, comm, sendbuf, recvbuf=None, op: Op = None):
        op = op or SUM
        if not _is_device(sendbuf):
            return self.host.reduce_scatter_block(comm, sendbuf, recvbuf, op)
        mode = self._mode("reduce_scatter_block", sendbuf, op,
                          allowed=self._ALL_ARMS
                          if op.name in _NP_FOLD
                          else ("native", "quant"))
        if mode == "quant":
            return self.dc.quant.reduce_scatter(sendbuf, op)
        if mode == "staged":
            h = self._stage_out(sendbuf)           # (R, R*b, *e)
            R = h.shape[0]
            b = h.shape[1] // R
            red = _NP_FOLD[op.name](h, axis=0)
            return self._stage_in(red.reshape((R, b) + h.shape[2:]))
        return self.dc.reduce_scatter(sendbuf, op)

    def scan(self, comm, sendbuf, recvbuf=None, op: Op = None):
        op = op or SUM
        if not _is_device(sendbuf):
            return self.host.scan(comm, sendbuf, recvbuf, op)
        mode = self._mode("scan", sendbuf, op,
                          allowed=("native", "staged")
                          if op.name in ("sum", "prod") else ("native",))
        if mode == "staged":
            h = self._stage_out(sendbuf)
            fn = np.cumsum if op.name == "sum" else np.cumprod
            return self._stage_in(fn(h, axis=0))
        return self.dc.scan(sendbuf, op)

    def exscan(self, comm, sendbuf, recvbuf=None, op: Op = None):
        op = op or SUM
        if not _is_device(sendbuf):
            return self.host.exscan(comm, sendbuf, recvbuf, op)
        mode = self._mode("exscan", sendbuf, op,
                          allowed=("native", "staged")
                          if op.name == "sum" else ("native",))
        if mode == "staged":
            h = self._stage_out(sendbuf)
            out = np.zeros_like(h)
            out[1:] = np.cumsum(h, axis=0)[:-1]
            return self._stage_in(out)
        return self.dc.scan(sendbuf, op, exclusive=True)

    def barrier(self, comm):
        # host barrier still needed for rank processes; device barrier syncs
        # the mesh. Do both: host ranks agree, devices quiesce.
        self.host.barrier(comm)
        self.dc.barrier()

    # -- neighborhood collectives (halo exchange) ---------------------------
    # Periodic cartesian topologies compile to 2·ndims ppermutes
    # (DeviceComm cart section ≙ coll_basic_neighbor_*.c specialized to
    # the torus); graph / non-periodic topologies keep the host path.

    def _cart_ok(self, comm, x, need_ndim: int) -> bool:
        topo = getattr(comm, "topo", None)
        return (topo is not None and getattr(topo, "kind", "") == "cart"
                and all(topo.periods) and self._rows_ok(x, need_ndim)
                and topo.size == x.shape[0] == self.dc.n)

    def _reject_canonical_noncart(self, comm, sendbuf) -> None:
        """In the single-controller regime (comm size 1, mesh of R) ANY
        canonical (R·k, ...) device layout that found no device path must
        not reach the host path — basic.neighbor_* would irecv from
        phantom ranks of a size-1 comm and hang. Fail loudly. Multi-rank
        comms with per-rank buffers keep the working host path."""
        if comm.size == 1 and self._rows_ok(sendbuf, 2):
            raise ValueError(
                "no device path for this neighborhood exchange (needs a "
                "cart or graph topology matching the mesh, default "
                "recvbuf, and rank-per-position rows); the host path "
                "cannot express a canonical device layout on a "
                "single-controller comm")

    def neighbor_allgather(self, comm, sendbuf, recvbuf=None):
        if recvbuf is None and self._cart_ok(comm, sendbuf, 2):
            return self.dc.neighbor_allgather_cart(sendbuf, comm.topo)
        if recvbuf is None and self._graph_ok(comm, sendbuf, 2):
            # arbitrary graphs / non-periodic carts: all_gather + masked
            # gather-map (padded to max degree; zeros past each degree)
            return self.dc.neighbor_allgather_graph(sendbuf, comm.topo)
        self._reject_canonical_noncart(comm, sendbuf)
        return self.host.basic.neighbor_allgather(
            comm, self._to_host(sendbuf), recvbuf)

    def _graph_ok(self, comm, x, need_ndim: int) -> bool:
        """The graph-path gate shared by the neighbor_* entries: cart or
        graph topology, canonical layout, rank-per-position rows."""
        topo = getattr(comm, "topo", None)
        return (topo is not None
                and getattr(topo, "kind", "") in ("cart", "graph")
                and self._rows_ok(x, need_ndim)
                and x.shape[0] == self.dc.n)

    def neighbor_allgatherv(self, comm, sendbuf, recvbuf=None, counts=None,
                            displs=None):
        """Ragged neighborhood allgather. COUNTS CONTRACT DIFFERS BY
        REGIME (the same canonical-vs-per-rank split as allgatherv):
        canonical device layout (R, cap, *e) takes PER-GLOBAL-RANK counts
        (length R) and returns (R, maxdeg, cap, *e) padded slots — slice
        slot k of row j by counts[in_neighbors(j)[k]]; the per-rank host
        path keeps MPI's per-in-neighbor counts/displs contract."""
        if (counts is not None and displs is None and recvbuf is None
                and self._graph_ok(comm, sendbuf, 2)
                and len(counts) == sendbuf.shape[0]
                and sendbuf.shape[1] >= max(int(c) for c in counts)):
            if self._cart_ok(comm, sendbuf, 2):
                # torus: padded rows travel whole on the neighbor-sparse
                # ppermute path (cart slot order == in_neighbors order)
                return self.dc.neighbor_allgather_cart(sendbuf, comm.topo)
            return self.dc.neighbor_allgather_graph(sendbuf, comm.topo)
        self._reject_canonical_noncart(comm, sendbuf)
        return self.host.basic.neighbor_allgatherv(
            comm, self._to_host(sendbuf), recvbuf, counts, displs)

    def neighbor_alltoall(self, comm, sendbuf, recvbuf=None):
        if recvbuf is None and self._cart_ok(comm, sendbuf, 3) \
                and sendbuf.shape[1] == 2 * len(comm.topo.dims):
            return self.dc.neighbor_alltoall_cart(sendbuf, comm.topo)
        if recvbuf is None and self._graph_ok(comm, sendbuf, 3):
            # ragged degrees (graphs, open carts): row-scatter +
            # alltoallv + slot reorder (DeviceComm graph section)
            return self.dc.neighbor_alltoall_graph(sendbuf, comm.topo)
        self._reject_canonical_noncart(comm, sendbuf)
        return self.host.basic.neighbor_alltoall(
            comm, self._to_host(sendbuf), recvbuf)

    # -- ragged / rooted entries: NATIVE ICI programs when the caller
    # presents the canonical padded device layout (DeviceComm docstring),
    # staged-host fallback otherwise. The reference implements these as
    # first-class host algorithms (coll_base_alltoallv.c:194 pairwise,
    # coll_base_allgatherv.c:95 bruck, coll_base_gather.c:41 binomial,
    # coll_base_scatter.c:63); the TPU-first shape is padded blocks + a
    # gather-map device argument (parallel/collectives.py ragged section),
    # so the EP/MoE alltoallv hot path never leaves ICI.

    def _to_host(self, x):
        """Host view of a maybe-device buffer: non-canonical layouts keep
        the host algorithm chain; ONE accounting path with _stage_out."""
        return self._stage_out(x) if _is_device(x) else x

    def _rows_ok(self, x, need_ndim: int) -> bool:
        """Canonical-layout gate: device buffer whose row dim covers the
        mesh axis (R % n == 0). Per-rank host-style buffers (the size>1
        process regime) miss the gate and stage — the same buffer-type
        dispatch check_addr does for host vs device."""
        if not _is_device(x) or x.ndim < need_ndim:
            return False
        R = x.shape[0]
        return R > 0 and R % self.dc.n == 0

    def allgatherv(self, comm, sendbuf, recvbuf=None, counts=None,
                   displs=None):
        if (counts is not None and displs is None and recvbuf is None
                and self._rows_ok(sendbuf, 2)
                and len(counts) == sendbuf.shape[0]
                and sendbuf.shape[1] >= max(int(c) for c in counts)):
            if self._mode("allgatherv", sendbuf) == "staged":
                return self._stage_in(_staged_allgatherv(
                    self._stage_out(sendbuf), counts))
            return self.dc.allgatherv(sendbuf, counts)
        return self.host.allgatherv(comm, self._to_host(sendbuf), recvbuf,
                                    counts, displs)

    def gather(self, comm, sendbuf, recvbuf=None, root: int = 0):
        if recvbuf is None and self._rows_ok(sendbuf, 2):
            if self._mode("gather", sendbuf) == "staged":
                # shared helper, NOT self.allgather: its own decision
                # would override this entry's staged pick
                return self._stage_in(
                    _staged_allgather(self._stage_out(sendbuf)))
            return self.dc.gather(sendbuf, root)
        return self.host.gather(comm, self._to_host(sendbuf), recvbuf, root)

    def gatherv(self, comm, sendbuf, recvbuf=None, counts=None, displs=None,
                root: int = 0):
        if (counts is not None and displs is None and recvbuf is None
                and self._rows_ok(sendbuf, 2)
                and len(counts) == sendbuf.shape[0]
                and sendbuf.shape[1] >= max(int(c) for c in counts)):
            if self._mode("gatherv", sendbuf) == "staged":
                return self._stage_in(_staged_allgatherv(
                    self._stage_out(sendbuf), counts))
            return self.dc.gatherv(sendbuf, counts, root)
        return self.host.basic.gatherv(comm, self._to_host(sendbuf), recvbuf,
                                       counts, displs, root)

    def scatter(self, comm, sendbuf, recvbuf=None, root: int = 0):
        if (recvbuf is None and self._rows_ok(sendbuf, 3)
                and sendbuf.shape[0] == sendbuf.shape[1]):
            if self._mode("scatter", sendbuf) == "staged":
                h = self._stage_out(sendbuf)       # (R, R, b, *e)
                return self._stage_in(np.ascontiguousarray(h[root]))
            return self.dc.scatter(sendbuf, root)
        return self.host.scatter(comm, self._to_host(sendbuf), recvbuf, root)

    def scatterv(self, comm, sendbuf, recvbuf, counts, displs=None,
                 root: int = 0):
        if (recvbuf is None and displs is None
                and self._rows_ok(sendbuf, 3)
                and sendbuf.shape[0] == sendbuf.shape[1]
                and len(counts) == sendbuf.shape[0]
                and sendbuf.shape[2] >= max(int(c) for c in counts)):
            if self._mode("scatterv", sendbuf) == "staged":
                h = self._stage_out(sendbuf)
                return self._stage_in(np.ascontiguousarray(h[root]))
            return self.dc.scatterv(sendbuf, counts, root)
        return self.host.basic.scatterv(comm, self._to_host(sendbuf),
                                        recvbuf, counts, displs, root)

    @staticmethod
    def _check_recvcounts(C, recvcounts):
        if recvcounts is None:
            return
        RC = np.asarray(recvcounts)
        # accept either the per-destination totals vector or the stacked
        # per-rank matrix (row j = what j receives from each source, C.T)
        ok = (np.array_equal(RC, C.T) if RC.ndim == 2
              else np.array_equal(RC.ravel(), C.sum(axis=0)))
        if not ok:
            raise ValueError(
                "alltoallv: recvcounts disagree with sendcounts "
                f"({recvcounts} vs column sums "
                f"{C.sum(axis=0).tolist()})")

    def alltoallv(self, comm, sendbuf, recvbuf, sendcounts, recvcounts,
                  sdispls=None, rdispls=None):
        C = np.asarray(sendcounts)
        if (recvbuf is None and sdispls is None and rdispls is None
                and C.ndim == 2 and C.shape[0] == C.shape[1]
                and self._rows_ok(sendbuf, 2) and sendbuf.ndim in (2, 3)
                and (sendbuf.ndim == 2
                     or sendbuf.shape[1] != sendbuf.shape[0])
                and sendbuf.shape[0] == C.shape[0]
                and sendbuf.shape[1] >= int(C.sum(axis=1).max())):
            # DENSE-ROWS form — MPI's actual buffer layout (contiguous
            # sends in destination order, default displacements), with
            # optional trailing elem dims (the EP token shape): the
            # sliced exchange never materializes the (R, R, cap) padded
            # blocks (alltoallv_from_rows; round-5). The one ambiguous
            # 3-D shape (L == R, indistinguishable from padded blocks)
            # keeps the block interpretation below.
            self._check_recvcounts(C, recvcounts)
            plan = self.dc.a2av_plan(sendbuf.shape, C)
            if self._mode("alltoallv", sendbuf, weights=C,
                          extra={"a2av_slice_cap": plan["slice_cap"],
                                 "a2av_scan_steps": plan["scan_steps"]},
                          ) == "staged":
                h = self._stage_out(sendbuf)           # (R, L, *e)
                out_cap = self.dc._bucket(
                    int(C.sum(axis=0).max()) if C.size else 1)
                return self._stage_in(
                    self.dc.compact_from_rows(h, C, out_cap))
            out, _tot = self.dc.alltoallv_from_rows(sendbuf, C)
            return out
        if (recvbuf is None and sdispls is None and rdispls is None
                and C.ndim == 2 and C.shape[0] == C.shape[1]
                and self._rows_ok(sendbuf, 3)
                and sendbuf.shape[0] == sendbuf.shape[1] == C.shape[0]
                and sendbuf.shape[2] >= int(C.max())):
            self._check_recvcounts(C, recvcounts)
            if self._mode("alltoallv", sendbuf, weights=C) == "staged":
                h = self._stage_out(sendbuf)       # (R, R, cap, *e)
                out_cap = self.dc._bucket(
                    int(C.sum(axis=0).max()) if h.shape[0] else 1)
                return self._stage_in(
                    self.dc.compact_ragged_blocks(h, C, out_cap))
            out, _tot = self.dc.alltoallv(sendbuf, C)
            return out
        return self.host.alltoallv(comm, self._to_host(sendbuf), recvbuf,
                                   sendcounts, recvcounts, sdispls, rdispls)

    def reduce_scatter(self, comm, sendbuf, recvbuf, counts, op: Op = None):
        op = op or SUM
        if (recvbuf is None and self._rows_ok(sendbuf, 2)
                and len(counts) == sendbuf.shape[0]
                and int(np.sum(counts)) == sendbuf.shape[1]):
            cs = [int(c) for c in counts]
            allowed = ["native"]
            if op.name in _NP_FOLD:
                allowed.append("staged")
            if len(set(cs)) == 1 and cs[0] > 0:
                allowed.append("quant")   # ragged counts: no quant layout
            mode = self._mode("reduce_scatter", sendbuf, op,
                              allowed=tuple(allowed))
            if mode == "quant":
                import jax.numpy as jnp
                out = self.dc.quant.reduce_scatter(sendbuf, op)
                cap = self.dc._bucket(cs[0])
                if cap != cs[0]:   # match reduce_scatter_v's padded cap
                    pad = [(0, 0), (0, cap - cs[0])]
                    pad += [(0, 0)] * (out.ndim - 2)
                    out = jnp.pad(out, pad)
                return out
            if mode == "staged":
                h = self._stage_out(sendbuf)       # (R, total, *e)
                red = _NP_FOLD[op.name](h, axis=0)
                cap = self.dc._bucket(max(int(c) for c in counts))
                out = np.zeros((h.shape[0], cap) + h.shape[2:], h.dtype)
                off = 0
                for i, c in enumerate(int(c) for c in counts):
                    out[i, :c] = red[off:off + c]
                    off += c
                return self._stage_in(out)
            return self.dc.reduce_scatter_v(sendbuf, counts, op)
        return self.host.reduce_scatter(comm, self._to_host(sendbuf),
                                        recvbuf, counts, op)


@component("coll", "xla", priority=80)
class XlaColl(Component):
    name = "xla"

    def query(self, comm):
        if getattr(comm, "device_comm", None) is None:
            return None, None
        try:
            import jax  # noqa: F401
        except ImportError:  # pragma: no cover
            return None, None
        return self.priority, XlaModule(comm)
