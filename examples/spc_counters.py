"""Software performance counters example (≙ examples/spc_example.c:
exercise some traffic, then read the SPC counters through the MPI_T-style
pvar interface).

Run:  python -m ompi_tpu.tools.tpurun -np 2 examples/spc_counters.py
"""

import numpy as np

from ompi_tpu import runtime
from ompi_tpu.mpit import pvar_read_all


def main() -> int:
    ctx = runtime.init()
    c = ctx.comm_world
    buf = np.zeros(1024, np.float64)
    for i in range(10):
        if ctx.rank == 0:
            c.send(np.full(1024, float(i)), 1, tag=1)
        elif ctx.rank == 1:
            c.recv(buf, 0, tag=1)
        c.barrier()
    if ctx.rank == 0:
        print("SPC pvars after 10 sends + barriers:", flush=True)
        for name, v in sorted(pvar_read_all(ctx).items()):
            if v:
                print(f"  {name} = {v}", flush=True)
    ctx.finalize()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
