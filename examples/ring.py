"""Ring message-passing example (≙ examples/ring_c.c:1 — the PR1 acceptance
workload, BASELINE.json configs[0]).

Run:  python -m ompi_tpu.tools.tpurun -np 4 examples/ring.py
"""

import sys
import time

import numpy as np

from ompi_tpu import runtime


def main() -> int:
    ctx = runtime.init()
    me, n = ctx.rank, ctx.size
    nxt, prv = (me + 1) % n, (me - 1) % n
    buf = np.zeros(1, np.int32)
    t0 = time.perf_counter()
    if me == 0:
        buf[0] = 10
        print(f"rank 0 sending {int(buf[0])} around a {n}-rank ring", flush=True)
        ctx.p2p.send(buf, dst=nxt, tag=201)
    while True:
        ctx.p2p.recv(buf, src=prv, tag=201)
        if me == 0:
            buf[0] -= 1
        ctx.p2p.send(buf, dst=nxt, tag=201)
        if buf[0] == 0:
            break
    if me == 0:
        ctx.p2p.recv(buf, src=prv, tag=201)
        dt = time.perf_counter() - t0
        print(f"rank 0 done: 10 laps x {n} hops in {dt*1e3:.2f} ms "
              f"({dt*1e6/(10*n):.1f} us/hop)", flush=True)
    runtime.finalize()
    return 0


if __name__ == "__main__":
    sys.exit(main())
