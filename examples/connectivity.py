"""All-pairs connectivity check (≙ examples/connectivity_c.c — the
reference's transport smoke test: every rank exchanges a token with every
other rank, proving the full peer matrix is wired).

Run:  python -m ompi_tpu.tools.tpurun -np 4 examples/connectivity.py
Add -v to print the per-pair transport (hook/comm_method's matrix role).
"""

import sys

import numpy as np

from ompi_tpu import runtime


def main() -> int:
    verbose = "-v" in sys.argv
    ctx = runtime.init()
    c = ctx.comm_world
    me, n = ctx.rank, ctx.size
    token = np.array([me], np.int32)
    peer_val = np.zeros(1, np.int32)
    # pairwise ordered exchange: lower rank sends first
    for peer in range(n):
        if peer == me:
            continue
        if me < peer:
            c.send(token, peer, tag=7)
            c.recv(peer_val, peer, tag=7)
        else:
            c.recv(peer_val, peer, tag=7)
            c.send(token, peer, tag=7)
        assert int(peer_val[0]) == peer, \
            f"rank {me}: bad token from {peer}: {int(peer_val[0])}"
    c.barrier()
    if me == 0:
        print(f"Connectivity test on {n} processes PASSED", flush=True)
        if verbose:
            for peer, tname in sorted(ctx.layer.transport_matrix().items()):
                print(f"  rank 0 -> rank {peer}: {tname}", flush=True)
    ctx.finalize()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
