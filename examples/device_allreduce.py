"""Multi-process device-plane allreduce — the north-star process model.

Run:  tpurun -np 4 --device-plane cpu examples/device_allreduce.py
      tpurun -np 4 --chips-per-rank 1 examples/device_allreduce.py   (pod)

Each rank is its own process owning its own device (the reference's
one-process-per-rank model, wired the PRRTE/PMIx way); the collective is a
compiled SPMD program across processes (ICI on TPU; gloo on the CPU test
fabric)."""

import numpy as np

from ompi_tpu import runtime
from ompi_tpu.op import SUM
from ompi_tpu.parallel import DeviceComm, init_device_plane, make_mesh

ctx = runtime.init()
init_device_plane(ctx)

import jax  # noqa: E402  (backend init must follow init_device_plane)

devs = jax.devices()
assert len(devs) >= ctx.size, (len(devs), ctx.size)
mesh = make_mesh({"x": len(devs)})
dc = DeviceComm(mesh, "x")

rows_per_rank = len(devs) // ctx.size
count = 1 << 14
local = np.full((rows_per_rank, count), float(ctx.rank + 1), np.float32)
x = dc.from_local(local)
y = dc.allreduce(x, SUM)
got = dc.to_local(y)

# every rank contributes rows_per_rank rows of (rank+1)
expect = rows_per_rank * sum(r + 1.0 for r in range(ctx.size))
assert got.shape == local.shape
assert np.all(got == expect), got[0, :4]

# the full component path: coll/xla outranks the host algorithms for device
# buffers on a mesh-attached communicator (north-star selection contract)
from ompi_tpu.parallel import attach_mesh  # noqa: E402

comm = ctx.comm_world
attach_mesh(comm, mesh, "x")
z = comm.coll.allreduce(comm, x, op=SUM)
assert np.all(dc.to_local(z) == expect)
comm.barrier()

print(f"rank {ctx.rank}: device-plane allreduce over {len(devs)} "
      f"process-devices ok ({got[0, 0]}), coll/xla path ok", flush=True)
runtime.finalize()
