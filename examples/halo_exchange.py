"""MPI-style halo exchange on a device mesh — the neighborhood-collective
face of the stencil workload (BASELINE.json configs[4]; examples/stencil.py
is the in-program shard_map form of the same physics).

A periodic cart of all visible devices holds one grid block per position
(the canonical (R, rows, cols) layout); each Jacobi sweep ships ONLY the
two facing boundary rows through ``comm.coll.neighbor_alltoall`` — which
the coll/xla component compiles to 2·ndims ``ppermute``s
(DeviceComm.neighbor_alltoall_cart, the halo data motion) — and folds
the received N/S halo rows into the 5-point update. Run:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/halo_exchange.py [n] [iters]
"""

import json
import sys
import time

import numpy as np


def main() -> int:
    from _platform import force_cpu_if_requested
    force_cpu_if_requested()
    import jax
    import jax.numpy as jnp

    from ompi_tpu import runtime
    from ompi_tpu.parallel import attach_mesh, make_mesh
    from ompi_tpu.topo import CartTopo

    n = int(sys.argv[1]) if len(sys.argv) > 1 else 512
    iters = int(sys.argv[2]) if len(sys.argv) > 2 else 20

    ctx = runtime.init()
    c = ctx.comm_world
    ndev = len(jax.devices())
    attach_mesh(c, make_mesh({"x": ndev}), "x")
    c.topo = CartTopo([ndev], [True])          # periodic ring of blocks
    dc = c.device_comm

    rows = max(n // ndev, 4)
    grid = dc.from_ranks([np.full((rows, n), float(i), np.float32)
                          for i in range(ndev)])

    def sweep(g):
        # facing rows only: block 0 (toward -1) = my top row, block 1
        # (toward +1) = my bottom row — 2·n floats per rank, not 2·rows·n
        faces = jnp.stack([g[:, :1, :], g[:, -1:, :]], axis=1)
        halo = c.coll.neighbor_alltoall(c, faces)        # (R, 2, 1, n)
        up = halo[:, 0]        # mirror slot: the block above's BOTTOM row
        down = halo[:, 1]      # the block below's top row
        padded = jnp.concatenate([up, g, down], axis=1)  # (R, rows+2, n)
        left = jnp.pad(g[:, :, :-1], ((0, 0), (0, 0), (1, 0)))
        right = jnp.pad(g[:, :, 1:], ((0, 0), (0, 0), (0, 1)))
        return 0.25 * (padded[:, :-2] + padded[:, 2:] + left + right)

    g = sweep(grid)                                      # warm/compile
    jax.block_until_ready(g)
    t0 = time.perf_counter()
    for _ in range(iters):
        g = sweep(g)
    val = float(jnp.ravel(g)[0])                         # read barrier
    dt = (time.perf_counter() - t0) / iters
    print(f"halo exchange: {ndev} blocks x ({rows}x{n}), "
          f"{iters} Jacobi sweeps, {dt*1e3:.2f} ms/sweep, first={val:.3f}")
    print(json.dumps({"metric": f"halo_jacobi_{ndev}x{rows}x{n}",
                      "value": round(1.0 / dt, 2), "unit": "sweeps/s"}))
    ctx.finalize()
    return 0


if __name__ == "__main__":
    sys.exit(main())
