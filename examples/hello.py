"""Hello world (≙ examples/hello_c.c).

Run:  python -m ompi_tpu.tools.tpurun -np 4 examples/hello.py
"""

from ompi_tpu import runtime


def main() -> int:
    ctx = runtime.init()
    print(f"Hello, world, I am {ctx.rank} of {ctx.size}", flush=True)
    ctx.finalize()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
