"""Minimal sharded training loop with checkpoint/resume.

The "switching user" end-to-end demo: build a mesh, shard the flagship
transformer dp×tp, run a few steps, checkpoint, restore, continue — the
TPU-native shape of what an MPI user would assemble from p2p + collectives
+ app-level checkpointing (SURVEY.md §2.6, §5.4).

Run (virtual 8-device mesh):
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python examples/train_minimal.py
"""

import os
import tempfile

import jax  # noqa: F401  (imported before any op)

from _platform import force_cpu_if_requested

force_cpu_if_requested()
import jax.numpy as jnp
import numpy as np

from ompi_tpu import ckpt
from ompi_tpu.models.transformer import (
    Config, init_params, make_train_step, shard_params)
from ompi_tpu.parallel import make_mesh


def main() -> int:
    ndev = len(jax.devices())
    tp = 2 if ndev % 2 == 0 else 1
    mesh = make_mesh({"dp": ndev // tp, "tp": tp})
    cfg = Config(vocab=128, d_model=64, n_layers=2, n_heads=4, head_dim=16,
                 d_ff=256, seq=32)
    params = shard_params(init_params(jax.random.key(0), cfg), mesh, cfg)
    init_opt, step = make_train_step(cfg, mesh)
    opt_state = init_opt(params)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab, (ndev, cfg.seq + 1)),
        jnp.int32)

    losses = []
    for i in range(4):
        params, opt_state, loss = step(params, opt_state, tokens)
        losses.append(float(loss))
    print(f"steps 0-3 loss: {losses[0]:.4f} -> {losses[-1]:.4f}", flush=True)
    assert losses[-1] < losses[0], "loss should fall on a memorizable batch"

    # checkpoint, clobber, restore, continue
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "ckpt")
        ckpt.save(path, params)
        restored = ckpt.restore(path, like=params)
        _p2, _o2, l2 = step(restored, opt_state, tokens)
        print(f"post-restore step loss: {float(l2):.4f}", flush=True)
        assert float(l2) <= losses[-1] + 1e-3
    print("train/checkpoint/resume PASSED", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
