"""SHMEM producer-consumer pipeline via put-with-signal (≙ the
shmem_put_signal pattern, oshmem/shmem/c/shmem_put_signal.c): each stage
pushes a chunk AND its ready-flag in ONE one-sided op — the signal is
ordered after the data, so the consumer needs no fence/quiet/barrier.

Run:  python -m ompi_tpu.tools.tpurun -np 3 examples/shmem_pipeline.py
"""

import numpy as np

from ompi_tpu import runtime, shmem

CHUNKS = 4
N = 16


def main() -> int:
    ctx = runtime.init()
    shmem.init(ctx)
    me, n = shmem.my_pe(), shmem.n_pes()
    data = shmem.smalloc((CHUNKS, N), np.float64)
    sig = shmem.smalloc((1,), np.int64)
    shmem.barrier_all()          # allocation visible everywhere

    nxt = (me + 1) % n
    for c in range(CHUNKS):
        if me != 0:
            # wait for chunk c from the left — no fence: the signal's
            # arrival ORDERING is the consistency point
            shmem.wait_until(sig, "ge", c + 1, timeout=30)
        if me == n - 1 and n > 1:
            continue                             # sink: verify below
        chunk = (np.arange(N, dtype=np.float64) + 100.0 * c if me == 0
                 else data.local[c] + 1.0)       # stage transform
        shmem.put_signal(data, chunk, sig, 1, nxt,
                         offset=c * N, sig_op=shmem.SIGNAL_ADD)
    shmem.barrier_all()
    if me == n - 1 and n > 1:
        # each intermediate stage (1..n-2) added 1.0 exactly once
        expect = np.arange(N) + 100.0 * (CHUNKS - 1) + (n - 2)
        got = data.local[CHUNKS - 1]
        assert np.allclose(got, expect), (got, expect)
        print(f"pipeline of {n} stages x {CHUNKS} chunks PASSED",
              flush=True)
    shmem.finalize()
    ctx.finalize()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
