"""Observability tour: pvars, decision audit, and a perfetto trace.

The successor to the old spc_counters example — the same MPI_T pvar
read-out, now with the trace subsystem walking through WHY a device
collective took the arm it took and WHERE the time went:

  1. host traffic (p2p + host collectives) feeding the SPC counters;
  2. a device-plane section on the 8-way virtual CPU mesh where an
     MPI_T cvar write forces the block-quantized allreduce arm;
  3. ``trace.explain_last`` — the decision audit with its precedence
     chain — plus the arm/wire-byte pvars;
  4. aggregate trace stats and a Chrome-trace JSON you can open in
     https://ui.perfetto.dev.

Run:  python -m ompi_tpu.tools.tpurun -np 2 examples/observability_tour.py
"""

import os

# the device section wants an 8-way virtual mesh on the host platform;
# both must be configured before jax initializes its backend
os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8"
                               ).strip()

import numpy as np

from ompi_tpu import mpit, runtime, trace


def host_traffic(ctx) -> None:
    """Section 1: classic SPC fodder — sends, recvs, host collectives."""
    c = ctx.comm_world
    buf = np.zeros(1024, np.float64)
    for i in range(10):
        if ctx.rank == 0:
            c.send(np.full(1024, float(i)), 1, tag=1)
        elif ctx.rank == 1:
            c.recv(buf, 0, tag=1)
        c.barrier()
    c.coll.allreduce(c, np.ones(256, np.float32))


def device_tour(ctx, cs) -> None:
    """Section 2+3: force the quantized arm through an MPI_T cvar write,
    dispatch one device collective, and read the audit back."""
    import jax
    import jax.numpy as jnp

    from ompi_tpu.parallel import attach_mesh, make_mesh

    attach_mesh(cs, make_mesh({"x": 8}), "x")
    mpit.cvar_write("coll_xla_allreduce_mode", "quant")
    try:
        host = np.random.default_rng(0).standard_normal(
            (8, 4096)).astype(np.float32)
        x = jax.device_put(jnp.asarray(host), cs.device_comm.sharding())
        cs.coll.allreduce(cs, x)
    finally:
        mpit.cvar_write("coll_xla_allreduce_mode", "")

    rec = trace.explain_last("allreduce")
    print(f"decision audit: {rec['op']} -> {rec['arm']} "
          f"because {rec['reason']}", flush=True)
    print(f"  logical {rec['nbytes']} B/rank, wire {rec['wire_bytes']} B "
          f"(ratio {rec['quant_ratio']:.3f}); "
          f"vetoed/skipped links: {rec['chain'] or 'none'}", flush=True)


def main() -> int:
    ctx = runtime.init()
    trace.enable()
    c = ctx.comm_world

    host_traffic(ctx)

    # per-rank size-1 sub-communicator: rank 0 runs the single-controller
    # device tour while the 8-device mesh stays a private plane
    cs = c.split(color=ctx.rank)
    if ctx.rank == 0:
        device_tour(ctx, cs)

        print("== pvar table (rank 0, nonzero) ==", flush=True)
        for name, v in sorted(mpit.pvar_read_all(ctx).items()):
            if v:
                print(f"  {name} = {v}", flush=True)

        print("== trace stats ==", flush=True)
        print(trace.format_stats(), flush=True)

        path = trace.save_chrome("observability_tour_trace.json")
        print(f"chrome trace written: {path} "
              "(open in ui.perfetto.dev)", flush=True)
    c.barrier()
    if ctx.rank == 0:
        print("observability tour PASSED", flush=True)
    trace.disable()
    ctx.finalize()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
