"""OpenSHMEM-style PGAS example (≙ examples/oshmem_symmetric_data.c /
hello_oshmem_c.c): symmetric allocation, one-sided put, fence, verify.

Run:  python -m ompi_tpu.tools.tpurun -np 4 examples/oshmem_hello.py
"""

import numpy as np

from ompi_tpu import runtime
from ompi_tpu import shmem


def main() -> int:
    ctx = runtime.init()
    shmem.init(ctx)
    me, n = shmem.my_pe(), shmem.n_pes()
    print(f"Hello, world, I am PE {me} of {n}", flush=True)
    # symmetric array: every PE writes its id into the NEXT PE's slot 0
    sym = shmem.smalloc((1,), np.int64)
    shmem.put(sym, np.array([me], np.int64), (me + 1) % n)
    shmem.quiet()
    shmem.barrier_all()
    got = int(sym.local[0])
    assert got == (me - 1) % n, f"PE {me}: expected {(me - 1) % n}, got {got}"
    if me == 0:
        print(f"symmetric put/verify on {n} PEs PASSED", flush=True)
    shmem.sfree(sym)
    shmem.finalize()
    ctx.finalize()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
