"""HPCG/miniFE-class stencil workload with device-resident reductions
(BASELINE.json configs[4]).

Conjugate gradient on the 2-D 5-point Laplacian, grid rows sharded over
the device mesh: the stencil's halo exchange is a pair of ``lax.ppermute``
neighbor shifts (the reference's MPI halo sendrecvs), and every CG dot
product is a ``lax.psum`` on-device allreduce — the HBM-resident reduction
the reference's coll/accelerator shim would have staged to host
(coll_accelerator_allreduce.c:31-60).

Run:  python examples/stencil.py [n] [iters]
Single-controller over all visible devices; prints residual + iterations/s
and one BENCH json line.
"""

import functools
import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ompi_tpu.jaxcompat import shard_map


def cg_solver(mesh: Mesh, n: int, iters: int):
    """Returns jit'd fn(b) -> (x, residual) running `iters` CG steps."""
    axis = "x"
    ndev = mesh.shape[axis]

    def halo_apply(u):
        """Local (rows, n) block → 5-point Laplacian with ppermute halos."""
        up = lax.ppermute(u[-1:], axis,
                          [(i, (i + 1) % ndev) for i in range(ndev)])
        down = lax.ppermute(u[:1], axis,
                            [(i, (i - 1) % ndev) for i in range(ndev)])
        i = lax.axis_index(axis)
        up = jnp.where(i == 0, jnp.zeros_like(up), up)          # Dirichlet
        down = jnp.where(i == ndev - 1, jnp.zeros_like(down), down)
        padded = jnp.concatenate([up, u, down], axis=0)
        lap = (4.0 * u
               - padded[:-2] - padded[2:]                        # N/S
               - jnp.pad(u[:, 1:], ((0, 0), (0, 1)))             # E
               - jnp.pad(u[:, :-1], ((0, 0), (1, 0))))           # W
        return lap

    def pdot(a, b):
        return lax.psum(jnp.vdot(a, b), axis)

    @functools.partial(shard_map, mesh=mesh, in_specs=P(axis),
                       out_specs=(P(axis), P()), check_vma=False)
    def solve(b):
        x = jnp.zeros_like(b)
        r = b
        p = r
        rr = pdot(r, r)

        def body(carry, _):
            x, r, p, rr = carry
            ap = halo_apply(p)
            alpha = rr / pdot(p, ap)
            x = x + alpha * p
            r = r - alpha * ap
            rr_new = pdot(r, r)
            p = r + (rr_new / rr) * p
            return (x, r, p, rr_new), None

        (x, r, _p, rr), _ = lax.scan(body, (x, r, p, rr), None,
                                     length=iters)
        return x, jnp.sqrt(rr)

    return jax.jit(solve)


def main() -> int:
    from _platform import force_cpu_if_requested
    force_cpu_if_requested()
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 2048
    iters = int(sys.argv[2]) if len(sys.argv) > 2 else 50
    devs = jax.devices()
    mesh = Mesh(np.asarray(devs), ("x",))
    n -= n % len(devs)
    b = jax.device_put(jnp.ones((n, n), jnp.float32),
                       NamedSharding(mesh, P("x")))
    solve = cg_solver(mesh, n, iters)
    x, res = solve(b)                     # compile + warm
    jax.block_until_ready((x, res))
    # time with a DIFFERENT rhs: identical (executable, input) pairs can be
    # served from a tunnel-side cache, which would fake the number
    b2 = jax.device_put(jnp.full((n, n), 2.0, jnp.float32),
                        NamedSharding(mesh, P("x")))
    t0 = time.perf_counter()
    x, res = solve(b2)
    res_val = float(res)    # a host READ is the completion barrier:
    dt = time.perf_counter() - t0
    # (block_until_ready alone has been observed returning early through
    # the tunneled TPU plugin; a D2H value read cannot lie)
    # 5-point stencil ≈ 6 flops/pt + CG vector ops ≈ 10 flops/pt per iter
    gflops = 16.0 * n * n * iters / dt / 1e9
    print(f"stencil CG: {n}x{n} grid, {len(devs)} device(s), "
          f"{iters} iters in {dt*1e3:.1f} ms "
          f"({iters/dt:.1f} it/s, ~{gflops:.1f} GF/s), "
          f"residual={res_val:.3e}")
    print(json.dumps({"metric": f"stencil_cg_{n}x{n}_{len(devs)}dev",
                      "value": round(iters / dt, 2), "unit": "iters/s"}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
