"""Shared example helper: honor JAX_PLATFORMS=cpu via jax.config.

Observed on this image: leaving platform selection to the ENV-sourced
default stalls in TPU-plugin discovery when the tunneled plugin wedges,
while an explicitly-SET config value initializes cpu directly
(A/B-verified; same stance as tests/conftest.py). No-op when the user
didn't ask for cpu.
"""

import os


def force_cpu_if_requested() -> None:
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
