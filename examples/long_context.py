"""Long-context sequence parallelism: ring attention + Ulysses.

The first-class long-context story (SURVEY.md §5.7): a sequence too long
for one device's memory is sharded over the ``sp`` mesh axis, and
attention runs as a ring — each step attends the local Q shard against the
visiting K/V shard, then rotates K/V one ICI hop (the identical neighbor-
exchange schedule as the reference's ring collectives,
coll_base_allreduce.c:344). Ulysses instead all-to-alls heads so every
device sees the full sequence for its head subset. Both are verified here
against whole-sequence attention, then timed.

Run (virtual 8-device mesh):
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python examples/long_context.py
"""

import os
import time

import jax  # noqa: F401  (imported before any op)

from _platform import force_cpu_if_requested

force_cpu_if_requested()
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ompi_tpu.parallel import make_mesh
from ompi_tpu.parallel.ring import attention_reference, ring_attention
from ompi_tpu.parallel.ulysses import ulysses_attention


def main() -> int:
    ndev = len(jax.devices())
    mesh = make_mesh({"sp": ndev})
    B, S, H, D = 2, 128 * ndev, 8, 32       # seq sharded ndev ways
    rng = jax.random.key(0)
    shape = (B, S, H, D)
    q = jax.random.normal(jax.random.fold_in(rng, 1), shape, jnp.float32)
    k = jax.random.normal(jax.random.fold_in(rng, 2), shape, jnp.float32)
    v = jax.random.normal(jax.random.fold_in(rng, 3), shape, jnp.float32)
    seq_sharded = NamedSharding(mesh, P(None, "sp", None, None))
    qs, ks, vs = (jax.device_put(x, seq_sharded) for x in (q, k, v))

    ref = attention_reference(q, k, v, causal=True)

    out_ring = ring_attention(qs, ks, vs, mesh, axis="sp", causal=True)
    np.testing.assert_allclose(np.asarray(out_ring), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    print(f"ring attention == reference (seq {S} over {ndev} shards)",
          flush=True)

    out_uly = ulysses_attention(qs, ks, vs, mesh, axis="sp", causal=True)
    np.testing.assert_allclose(np.asarray(out_uly), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    print("ulysses attention == reference", flush=True)

    for name, fn in (("ring", lambda: ring_attention(qs, ks, vs, mesh,
                                                     axis="sp", causal=True)),
                     ("ulysses", lambda: ulysses_attention(
                         qs, ks, vs, mesh, axis="sp", causal=True))):
        fn()[0, 0, 0, 0].block_until_ready()       # compile + warm
        t0 = time.perf_counter()
        reps = 5
        for _ in range(reps):
            out = fn()
        float(jnp.ravel(out)[0])
        print(f"{name}: {(time.perf_counter() - t0) / reps * 1e3:.1f} "
              f"ms/call", flush=True)
    print("long-context example PASSED", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
