# Repo-level developer entry points. The tier-1 gate is THE acceptance
# command (ROADMAP.md): the full CPU test run, collection errors
# surfaced — a PR that introduces a new collection error fails here even
# when every collected test passes.

SHELL := /bin/bash

.PHONY: tier1 quant-tests trace-tests overlap-tests doctor-tests \
	health-tests perf-tests traffic-tests hier-tests numerics-tests \
	reshard-tests analysis-tests ft-elastic-tests moe-tests \
	serve-tests decode-tests policy-tests fleet-tests request-tests \
	history-tests comm-lint bench-compare

# the health-plane gate runs FIRST: its suite is seconds-cheap and its
# end-to-end probe (an 8-rank fleet with an injected one-rank stall the
# watchdog must attribute within 2x its timeout) guards the tier the
# rest of the run leans on when something hangs; the perf-plane gate
# rides along — its suite is also seconds-cheap and its probe banks the
# trajectory artifact bench-compare diffs against; the traffic-plane
# gate closes the loop — its probe injects a skewed ppermute an 8-dev
# fleet's matrix must attribute to the exact hot edge, conservation held;
# the hier gate rides last — its probe folds the 8 devices into a
# simulated 2x4 ICI×DCN pod and fails unless the hier arm beats flat
# wall-clock while moving exactly 1/n_inner of the bytes on the slow
# plane; the numerics gate watches the payload itself — its probe
# injects a NaN and a bit flip the plane must attribute to the exact
# (rank, step, op) / (step, bucket, rank); the reshard gate closes the
# sequence — its probe times a 4-transition layout-conversion suite
# against the host round-trip it replaces and fails unless the device
# plans win with every step decision-audited and conservation held;
# the analysis gate runs before any of it — the static verifier and
# comm-lint are pure CPU/AST work that catches a malformed collective
# program or an unaudited dispatch path without spending a single
# measured second
tier1: analysis-tests health-tests perf-tests traffic-tests hier-tests \
	numerics-tests reshard-tests ft-elastic-tests moe-tests serve-tests \
	decode-tests policy-tests fleet-tests request-tests history-tests
	set -o pipefail; rm -f /tmp/_t1.log; \
	timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
	  -m 'not slow' --continue-on-collection-errors \
	  -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 \
	  | tee /tmp/_t1.log; rc=$${PIPESTATUS[0]}; \
	echo DOTS_PASSED=$$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$$' /tmp/_t1.log | tr -cd . | wc -c); \
	new_collect=$$(grep -ac 'ERROR collecting' /tmp/_t1.log || true); \
	if [ "$$new_collect" -gt 0 ]; then \
	  echo "tier1: $$new_collect collection error(s) — failing"; exit 1; \
	fi; \
	exit $$rc

# the quantized-tier suite alone (fast iteration on coll/quant work)
quant-tests:
	env JAX_PLATFORMS=cpu python -m pytest tests/test_quant_coll.py -q \
	  -p no:cacheprovider -p no:randomly

# the tracing + decision-audit suite alone (fast iteration on
# ompi_tpu/trace work: audit events, Chrome export, pvars, overflow)
trace-tests:
	env JAX_PLATFORMS=cpu python -m pytest tests/test_observability.py \
	  -q -k "trace or wire or handle" -p no:cacheprovider -p no:randomly

# the fleet flight-recorder tier: cross-rank merge, straggler doctor,
# mpisync, Prometheus exposition — then the end-to-end probe (an 8-rank
# fleet with an injected straggler the doctor must attribute)
doctor-tests:
	env JAX_PLATFORMS=cpu python -m pytest tests/test_doctor.py -q \
	  -p no:cacheprovider -p no:randomly
	env JAX_PLATFORMS=cpu python bench.py --doctor

# the live-health tier: watchdog + desync sentinel + HTTP endpoint
# suite, then the end-to-end stall-attribution probe (exits nonzero
# unless the sentinel names the stalled rank and dumps land)
health-tests:
	env JAX_PLATFORMS=cpu python -m pytest tests/test_health.py -q \
	  -p no:cacheprovider -p no:randomly
	env JAX_PLATFORMS=cpu python bench.py --watchdog

# the continuous-performance tier: cost model + goodput ledger + sentry
# suite, then the end-to-end probe (measures the goodput split through
# the unsynced-floor methodology, banks BENCH_r06.json and the
# PERF_LEDGER, exits nonzero on unmeasured columns)
perf-tests:
	env JAX_PLATFORMS=cpu python -m pytest tests/test_perf.py -q \
	  -p no:cacheprovider -p no:randomly
	env JAX_PLATFORMS=cpu python bench.py --goodput

# the topology-traffic tier: per-edge attribution + ICI/DCN plane
# ledger + hot-link sentry suite, then the end-to-end probe (uniform
# ring background plus a skewed push_row lane the sentry must trip on
# EXACTLY once, naming (src, dst); banks TRAFFIC_<platform>.json; exits
# nonzero on any conservation residue)
traffic-tests:
	env JAX_PLATFORMS=cpu python -m pytest tests/test_traffic.py -q \
	  -p no:cacheprovider -p no:randomly
	env JAX_PLATFORMS=cpu python bench.py --traffic

# the hierarchical multi-plane tier: hier/hier+quant decision arms,
# '<coll>@<plane>' rule rows, padding fix, simulated-DCN classification
# — then the end-to-end pod probe (8 devices as a 2x4 outer×inner mesh
# with the outer axis DCN-skewed; exits nonzero unless hier beats flat
# and the outer stage carries exactly 1/n_inner of the flat-arm bytes)
hier-tests:
	env JAX_PLATFORMS=cpu python -m pytest tests/test_hier.py -q \
	  -p no:cacheprovider -p no:randomly
	env JAX_PLATFORMS=cpu python bench.py --pod

# the numerics tier: probes/sentries/divergence-auditor suite, then the
# end-to-end probe (8-dev comm with an injected NaN + a bit-flipped
# replica; exits nonzero unless both are attributed to exactly the
# injected (rank, step, op) and (step, bucket, rank); banks
# NUMERICS_<platform>.json)
numerics-tests:
	env JAX_PLATFORMS=cpu python -m pytest tests/test_numerics.py -q \
	  -p no:cacheprovider -p no:randomly
	env JAX_PLATFORMS=cpu python bench.py --numerics

# the redistribution tier: plan compiler + executable cache + audit
# suite, then the end-to-end probe (8 devices; a 4-transition 32 MiB
# layout-conversion suite timed against the staged host round-trip it
# replaces; exits nonzero unless the device plans win wall-clock, every
# plan stays within its peak-bytes bound, every step emitted exactly
# one decide:reshard event, and the traffic matrix's reshard bytes
# equal the audited wire bytes; banks RESHARD_<platform>.json)
reshard-tests:
	env JAX_PLATFORMS=cpu python -m pytest tests/test_reshard.py -q \
	  -p no:cacheprovider -p no:randomly
	env JAX_PLATFORMS=cpu python bench.py --reshard

# the elastic fault-tolerance tier: cross-mesh reshard planner +
# peer-shadow ring + ElasticTrainer recovery loop + chaos injector
# suite, then the end-to-end probe (8 devices; a deterministic kill of
# mesh position 3 at step 7 the trainer must survive by shrinking to
# the 4-device mesh and re-laying state from the peer shadows with ZERO
# checkpoint reads; exits nonzero unless the injected rank is named by
# exactly one audited ft_recovery decision, recovery lands within the
# steps-lost budget, the losses track an uninterrupted baseline, and
# traffic conservation holds; banks ELASTIC_<platform>.json)
ft-elastic-tests:
	env JAX_PLATFORMS=cpu python -m pytest tests/test_elastic.py -q \
	  -p no:cacheprovider -p no:randomly
	env JAX_PLATFORMS=cpu python bench.py --elastic

# the token-proportional MoE tier: ragged dispatch/combine round-trip
# vs the host oracle + moe_block_ep arm/conservation suite + hot-expert
# sentry/adaptation loop, then the end-to-end probe (8 devices, einsum
# vs ragged vs ragged+hier on uniform AND skewed routing; exits nonzero
# unless the skewed phase trips the hot-expert sentry EXACTLY once, a
# capacity adaptation rebalances it away within the probe, ragged wire
# bytes stay token-proportional, and traffic conservation holds; banks
# MOE_<platform>.json + a BASELINE.md row)
moe-tests:
	env JAX_PLATFORMS=cpu python -m pytest tests/test_moe_ep.py -q \
	  -p no:cacheprovider -p no:randomly
	env JAX_PLATFORMS=cpu python bench.py --moe

# the serving tier: paged-KV-cache accounting + prefill/decode greedy
# parity vs the train forward() + convert_params round-trip with the
# per-weight reshard plan pinned + continuous-vs-static scheduler +
# decode_ag/decode_rs decision audit/conservation suite, then the
# end-to-end probe (8 devices, one Poisson stream through both
# batching policies + a teacher-forced native-vs-int8 window; exits
# nonzero unless continuous beats static on tokens/s with identical
# per-request outputs, quant shrinks decode wire >= 3x at parity, and
# every audited byte conserves; banks SERVE_<platform>.json +
# BASELINE.md rows)
serve-tests:
	env JAX_PLATFORMS=cpu python -m pytest tests/test_serving.py -q \
	  -p no:cacheprovider -p no:randomly
	env JAX_PLATFORMS=cpu python bench.py --serve

# the decode fast-path tier: fused collective-matmul decode program
# (eager-vs-fused parity, 11 -> 2 eager dispatches/step, commgraph
# static-vs-runtime byte proof on 2/4/8-dev meshes) + speculative
# draft/verify windows (token-stream identity, MEASURED acceptance) +
# pad-past-native quant veto + learned decode arms + MoE decode parity
# + comm-lint over the serving modules; the --serve probe's fused/
# speculative/learned phases are its end-to-end gate (shares the
# serve-tests probe so the banked SERVE_<platform>.json stays one
# artifact)
decode-tests:
	env JAX_PLATFORMS=cpu python -m pytest tests/test_decode.py -q \
	  -p no:cacheprovider -p no:randomly
	env JAX_PLATFORMS=cpu python bench.py --serve

# the policy-plane tier: verdict bus + statically pre-verified action
# space + fleet-consistent vote + audited observe->decide->act suite,
# then the self-driving probe (8 devices; a chaos-slowed allreduce link
# plus a forced quant-SNR drop the plane must retune PAST without a
# restart — exits nonzero unless the arm demotes to quant fleet-wide,
# recovered goodput beats the degraded floor under the SAME chaos,
# zero steps drop, every decide:policy event names its causing verdict
# (100% attribution) and the SNR verdict halves the quant block; banks
# POLICY_<platform>.json + a provenance-commented DEVICE_RULES row)
policy-tests:
	env JAX_PLATFORMS=cpu python -m pytest tests/test_policy.py -q \
	  -p no:cacheprovider -p no:randomly
	env JAX_PLATFORMS=cpu python bench.py --selfdrive

# the serving-fleet gate: KV-page migration round-trip + router +
# hot_replica sentry suite, then the end-to-end probe (one Poisson
# stream through colocated tp=8 vs prefill/decode-split tp=4 replicas
# at the SAME 8 chips; exits nonzero unless the split beats colocated
# on p99 ITL with IDENTICAL token streams, every migration within the
# reshard peak bound and fleet-wide conservation closed; banks
# FLEET_<platform>.json)
fleet-tests:
	env JAX_PLATFORMS=cpu python -m pytest tests/test_fleet.py -q \
	  -p no:cacheprovider -p no:randomly
	env JAX_PLATFORMS=cpu python bench.py --fleet

# the request-plane gate: span-tree stitching + conservation + exemplar
# reservoir + SLO-judge suite, then the end-to-end probe (a chaos-
# delayed migration lane and a slowed prefill replica on the same
# 8-chip disaggregated fleet; exits nonzero unless each degradation is
# attributed to its true stage at p99, every sampled request's stage
# sum matches e2e within clock confidence on the merged timeline, and
# each breach episode lands exactly one slo_breach verdict answered by
# one audited decide:fleet_route; banks REQUESTS_<platform>.json)
request-tests:
	env JAX_PLATFORMS=cpu python -m pytest tests/test_requests.py -q \
	  -p no:cacheprovider -p no:randomly
	env JAX_PLATFORMS=cpu python bench.py --slo

# the history tier: the fleet-lifetime run ledger + deterministic
# changepoint kernel suite, then the end-to-end probe (a 12-run
# synthetic trajectory with an injected -20% step and -2%/run drift
# the detector must attribute to exactly those two (metric, run_id)
# changepoints with zero false positives, the history_regression
# verdict answered by one audited decide:policy, and the episode
# re-armed after a recovered run; banks HISTORY_<platform>.json)
history-tests:
	env JAX_PLATFORMS=cpu python -m pytest tests/test_history.py -q \
	  -p no:cacheprovider -p no:randomly
	env JAX_PLATFORMS=cpu python bench.py --history

# the static-analysis tier: jaxpr collective extraction + SPMD checks
# + comm-lint + DEVICE_RULES validator suite, then the end-to-end probe
# (extracts the flagship train step's and a reshard plan's collective
# programs on the 8-dev mesh and exits nonzero unless the static wire
# prediction equals the runtime traffic attribution byte-for-byte;
# banks ANALYZE_<platform>.json) — plus the lint gate itself
analysis-tests: comm-lint
	env JAX_PLATFORMS=cpu python -m pytest tests/test_analysis.py -q \
	  -p no:cacheprovider -p no:randomly
	env JAX_PLATFORMS=cpu python bench.py --analyze

# repo-invariant comm-lint (rules CL001-CL008, justified waivers only)
# plus the DEVICE_RULES grammar validator; nonzero on any unwaived
# finding — cheap enough to run on every edit
comm-lint:
	python -m ompi_tpu.analysis.lint ompi_tpu
	python -m ompi_tpu.analysis.rules DEVICE_RULES.txt

# regression gate over the banked trajectory artifact: non-zero exit
# names every phase whose busbw/goodput/MFU column lost >10% (run it
# with OLD= NEW= to diff two arbitrary banked artifacts)
OLD ?= BENCH_r06.json
NEW ?= BENCH_r06.json
bench-compare:
	python bench.py --compare $(OLD) $(NEW)

# the comm/compute overlap tier: bucketed grad sync + collective-matmul
# rings, INCLUDING the multi-device tests marked slow (excluded from
# tier-1 to keep its wall clock inside the 870 s budget)
overlap-tests:
	env JAX_PLATFORMS=cpu python -m pytest tests/test_overlap.py \
	  tests/test_ops.py -k "CollectiveMatmul or overlap" -q \
	  -p no:cacheprovider -p no:randomly
